"""Preemption-tolerant multi-host training: liveness, coordinated
checkpoint-on-preempt, and the supervising relauncher.

On real pods preemption is the common case, not the exception (the
MLPerf TPU-v3 Pods playbook, ROADMAP item 3) — yet one lost host, one
stalled collective, or one dead process used to kill the whole
``train_dist.py`` job with no recovery. This module closes that tier
with three cooperating pieces, all file-coordinated over the job's
shared workdir (localhost dirs on the CPU smoke, GCS/NFS on a pod) so
no side channel beyond the filesystem every host already shares is
needed:

:class:`ClusterMember` (in-worker, attached to the Trainer)
    Writes throttled per-host heartbeats (``hb-<host>.json``: step,
    epoch, status) and speaks the **coordinated save-barrier
    protocol**. A host holding the preemption notice (SIGTERM)
    publishes a single first-writer-wins ``barrier.json`` naming a stop
    step ``cur + barrier_lead``; every host polls the marker once per
    batch, keeps DISPATCHING to exactly that step (the Trainer's forced
    fetch cadence bounds cross-host dispatch skew well under
    ``barrier_lead``, so nobody can be past the stop when they first
    see it), then rendezvouses on ``arrive-<host>.json`` files and
    commits ONE collective mid-epoch checkpoint through the PR 4
    manifest machinery. A bounded arrive-wait that times out (peer
    died post-notice) degrades to **no save** — resume then falls back
    to the newest commonly-verified epoch instead of wedging inside a
    dead collective.

:class:`HostLedger` (read side)
    Supervisor view of the heartbeats: alive set, per-host step/age,
    max step lag. Publishes the ``cluster_host_alive`` /
    ``cluster_step_lag`` obs gauges.

:class:`ClusterSupervisor` (the parent ``train_dist.py --supervise N``)
    Spawns one worker process per logical host, watches the ledger,
    and drives recovery: straggler detection (heartbeat age over
    budget -> logged + counted, instead of a barrier that hangs),
    heartbeat-dead hosts (kill the generation, relaunch from the
    newest commonly-verified epoch — ``train/manifest.py``'s pure-hash
    scan, no Orbax/jax in the parent), and **deterministic elastic
    resume**: a gracefully preempted host is removed from the fleet
    and the job relaunches on the survivors with ``--resume`` — the
    loader's file-shard assignment re-partitions over the new host
    count (``tf.data list_files(seed).shard`` + ``imagenet.
    _TrainShardFactory``: disjoint cover, no loss, no duplication) and
    ``KeySeq``'s epoch-folded global key + ``skip`` replay the exact
    PRNG draws, so the resumed trajectory is the uninterrupted one.
    Chaos sites ``host_preempt``/``host_stall`` (``faults.py``) are
    consulted once per observed cluster step, so drills replay
    bit-identically; the grep-stable exit line is
    ``[cluster] preemptions=P resumes=R stragglers=S host_deaths=D``.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

from deepvision_tpu.obs.distributed import flight_dump, get_flight_recorder
from deepvision_tpu.obs.metrics import default_registry

__all__ = [
    "ClusterMember",
    "ClusterSupervisor",
    "HostLedger",
    "argv_value",
    "select_resume_epoch",
]


def argv_value(argv, *flags) -> str | None:
    """Read a flag's value out of a raw train.py argv in BOTH argparse
    spellings (``--workdir X`` and ``--workdir=X``) — the supervisor's
    checkpoint discovery must agree with what argparse will see, or a
    relaunch silently drops ``--resume`` and restarts from scratch."""
    for i, a in enumerate(argv):
        for f in flags:
            if a == f and i + 1 < len(argv):
                return argv[i + 1]
            if a.startswith(f + "="):
                return a.split("=", 1)[1]
    return None

# default stop-step lead of the save barrier. The Trainer derives its
# forced fetch cadence in cluster mode as max(1, min(32, lead // 2)),
# so the invariant "lead exceeds twice the fetch cadence" holds BY
# CONSTRUCTION for any lead >= 2: a host can never be more than one
# cadence of dispatches ahead of the slowest peer (its own fetches
# block on everyone's dispatched collectives), so every host observes
# the marker strictly before its dispatch count reaches the stop step,
# and if any host already FINISHED the epoch loop (peers within one
# cadence of the end) the stop lands past the epoch end for everyone,
# degrading consistently to exit-after-epoch-checkpoint. Small leads
# (smoke/bench use 3 for a tight mid-epoch stop) trade feed overlap
# for stop precision — the cadence becomes per-batch; 64 keeps the
# default cadence at the watchdog's 32.
BARRIER_LEAD = 64
ENV_DIR = "DVTPU_CLUSTER_DIR"
ENV_HOST = "DVTPU_CLUSTER_HOST"
ENV_NHOSTS = "DVTPU_CLUSTER_NHOSTS"
ENV_LEAD = "DVTPU_CLUSTER_BARRIER_LEAD"
ENV_TIMEOUT = "DVTPU_CLUSTER_BARRIER_TIMEOUT"
# the process's ORIGINAL host id — stable across elastic relaunches
# (generation indices are not), so ':hostH'-targeted sdc drills and the
# quarantine ledger name the same physical host forever
ENV_ORIG_HOST = "DVTPU_CLUSTER_ORIG_HOST"
# the generation index, exported so every worker's tracer stamps its
# spans (host, generation) — one training step is correlatable across
# hosts and relaunches on the merged fleet timeline
ENV_GEN = "DVTPU_CLUSTER_GEN"
# replay-bisection mode: train deterministically to this RUN step
# (auditing on the way), then exit 0 without saving — the audit files
# are the replay's verdict (resilience/sentinel.py module docstring)
ENV_REPLAY = "DVTPU_SENTINEL_REPLAY"
ENV_QUIESCE = "DVTPU_SDC_QUIESCE"


def _atomic_write_json(path: Path, obj: dict) -> None:
    """tmp + os.replace, unique tmp per (pid): readers never see a
    torn heartbeat/marker."""
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    tmp.write_text(json.dumps(obj))
    os.replace(tmp, path)


def _create_once_json(path: Path, obj: dict) -> bool:
    """First-writer-wins atomic create (O_EXCL through a unique tmp +
    link-style create): True when THIS caller's content landed."""
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False
    try:
        os.write(fd, json.dumps(obj).encode())
    finally:
        os.close(fd)
    return True


def _read_json(path: Path) -> dict | None:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ClusterMember:
    """One host's handle on the coordination directory (worker side).

    Pure file ops — no jax — so it is constructible before (and
    independent of) ``jax.distributed.initialize``; the Trainer drives
    the protocol (``attach_cluster``)."""

    def __init__(self, directory: str | Path, host: int, nhosts: int, *,
                 barrier_lead: int = BARRIER_LEAD,
                 barrier_timeout_s: float = 30.0,
                 beat_interval_s: float = 0.2,
                 orig_host: int | None = None,
                 metrics_interval_s: float = 2.0):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.host = int(host)
        self.nhosts = int(nhosts)
        if not 0 <= self.host < self.nhosts:
            raise ValueError(
                f"host {host} outside the fleet of {nhosts}")
        # the stable physical identity (generation indices reshuffle on
        # elastic resume): metric labels and spool rows carry this one
        self.orig_host = int(orig_host) if orig_host is not None \
            else self.host
        self.barrier_lead = int(barrier_lead)
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.beat_interval_s = float(beat_interval_s)
        self.metrics_interval_s = float(metrics_interval_s)
        self._last_beat = 0.0
        self._last_metrics = 0.0
        self._last_epoch = -1
        self._barrier_cache: dict | None = None
        self._own_audits: dict[int, dict] = {}
        self._audits_compared: set[int] = set()
        self._spool = None

    @classmethod
    def from_env(cls, environ=os.environ) -> "ClusterMember | None":
        """The launcher->worker wiring: ``train_dist.py --supervise``
        exports the coordination dir + identity; ``train.py`` attaches
        the member to the Trainer when present. The worker side of the
        fleet observability attaches here too: tracer labels, span
        spool, flight recorder."""
        d = environ.get(ENV_DIR)
        if not d:
            return None
        host = int(environ.get(ENV_HOST, "0"))
        member = cls(
            d, host,
            int(environ.get(ENV_NHOSTS, "1")),
            barrier_lead=int(environ.get(ENV_LEAD, str(BARRIER_LEAD))),
            barrier_timeout_s=float(environ.get(ENV_TIMEOUT, "30")),
            orig_host=int(environ.get(ENV_ORIG_HOST, str(host))),
        )
        member.attach_observability(environ)
        return member

    def attach_observability(self, environ=os.environ) -> None:
        """Fleet-wide observability, worker side (obs/distributed.py):
        stamp the tracer with (host, generation), attach the span spool
        the supervisor requested via ``DVTPU_TRACE_SPOOL`` (the
        crash-safe on-disk ring that survives even a SIGKILL — the
        quarantine black box), and install the flight recorder dumping
        into the coordination dir on trip/divergence/preempt."""
        try:
            from deepvision_tpu.obs.distributed import (
                enable_spool_from_env,
                install_flight_recorder,
            )

            self._spool = enable_spool_from_env(
                role=f"host{self.orig_host}", environ=environ)
            install_flight_recorder(
                self.directory,
                meta={"role": "trainer", "host": self.orig_host})
        except Exception:
            pass  # observability must never take a worker down

    # -- liveness --------------------------------------------------------
    def beat(self, step: int, epoch: int | None = None,
             status: str = "run", force: bool = False) -> None:
        """Throttled heartbeat (one small atomic write per
        ``beat_interval_s`` at most — per-batch calls are cheap)."""
        now = time.time()
        if not force and now - self._last_beat < self.beat_interval_s:
            return
        if epoch is None:
            epoch = self._last_epoch
        self._last_epoch = epoch
        self._last_beat = now
        _atomic_write_json(
            self.directory / f"hb-{self.host}.json",
            {"host": self.host, "pid": os.getpid(), "step": int(step),
             "epoch": int(epoch), "status": status, "time": now})
        if now - self._last_metrics >= self.metrics_interval_s:
            self._last_metrics = now
            self.publish_metrics(step, now=now)

    def publish_metrics(self, step: int, now: float | None = None) -> None:
        """Federated-metrics publication, riding the heartbeat cadence:
        an atomic typed registry dump (``metrics-<index>.json``) the
        supervisor scrapes into its ``--metrics-port`` surface with
        ``{host=<orig>}`` labels, plus a flight-recorder note so the
        black box carries per-interval metric deltas keyed by step."""
        try:
            _atomic_write_json(
                self.directory / f"metrics-{self.host}.json",
                {"host": self.orig_host, "index": self.host,
                 "time": now if now is not None else time.time(),
                 "dump": default_registry().dump()})
            rec = get_flight_recorder()
            if rec is not None:
                rec.note("beat", step=int(step))
        except Exception:
            pass  # the scrape surface must never take the worker down

    # -- save-barrier protocol -------------------------------------------
    def write_barrier(self, epoch: int, stop_step: int) -> dict:
        """Publish the cluster-wide stop point (first writer wins —
        concurrent notices collapse to one barrier); returns the
        winning marker. The notice holder dumps its flight recorder —
        this host is leaving (SIGTERM), so its black box goes to disk
        while it still can."""
        flight_dump("sigterm-preempt")
        _create_once_json(
            self.directory / "barrier.json",
            {"epoch": int(epoch), "stop_step": int(stop_step),
             "by": self.host})
        return self.read_barrier()

    def write_after_epoch(self, epoch: int) -> dict:
        """Exit-after-epoch marker for notices that land outside the
        step loop (validate/save): peers at the same boundary exit
        after their epoch checkpoint; peers already past it degrade."""
        flight_dump("sigterm-preempt")
        _create_once_json(
            self.directory / "barrier.json",
            {"after_epoch": int(epoch), "by": self.host})
        return self.read_barrier()

    def read_barrier(self) -> dict | None:
        """The (single, immutable) barrier marker, cached once seen."""
        if self._barrier_cache is None:
            self._barrier_cache = _read_json(
                self.directory / "barrier.json")
        return self._barrier_cache

    def arrive(self, step: int) -> None:
        _atomic_write_json(
            self.directory / f"arrive-{self.host}.json",
            {"host": self.host, "step": int(step)})

    def await_all_arrived(self, *, timeout_s: float | None = None) -> bool:
        """Poll (file reads only — NEVER device fetches, so a waiting
        host cannot wedge a peer) until every fleet member arrived;
        False on timeout (a peer died post-notice: degrade to no-save)."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.barrier_timeout_s)
        while True:
            if all((self.directory / f"arrive-{h}.json").exists()
                   for h in range(self.nhosts)):
                return True
            if time.monotonic() >= deadline:
                return False
            self.beat(0, status="barrier")
            time.sleep(0.05)

    def mark_committed(self, epoch: int, step: int) -> None:
        """Record that THIS host's coordinated save committed; the
        supervisor requires all-hosts markers with one common step to
        call the preemption save trustworthy. Every host exits after
        this — the black box of its final window rides along."""
        flight_dump("preempt-save")
        _atomic_write_json(
            self.directory / f"commit-{self.host}.json",
            {"host": self.host, "epoch": int(epoch), "step": int(step)})

    def coordinate_clear(self, tag: str, clear_fn,
                         timeout_s: float = 30.0) -> bool:
        """Single-writer clear rendezvous: host 0 runs ``clear_fn`` and
        publishes ``cleared-<tag>``; peers wait for the marker (so no
        peer constructs a checkpoint manager inside a directory host 0
        is still rmtree-ing). The flock the single-host path uses would
        DEADLOCK here — a collective save needs every host inside
        save() concurrently."""
        marker = self.directory / f"cleared-{tag}.json"
        if self.host == 0:
            clear_fn()
            _atomic_write_json(marker, {"by": 0, "time": time.time()})
            return True
        deadline = time.monotonic() + timeout_s
        while not marker.exists():
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.05)
        return True

    def commit_records(self) -> list[dict]:
        return [r for h in range(self.nhosts)
                if (r := _read_json(
                    self.directory / f"commit-{h}.json")) is not None]

    # -- cross-host state-agreement audit (silent-failure defense) -------
    def record_audit(self, step: int, fp: dict) -> dict | None:
        """Publish this host's state fingerprint for audit ``step`` and
        compare every audit step for which ALL hosts have now
        published (lag-tolerant: a host ahead of its peers banks its
        own audits and compares them as the peer files land — file
        reads only, never a device fetch, so auditing can never wedge
        a peer's collectives). Returns ``{"step", "fps"}`` on the
        FIRST step whose fingerprints disagree, else None."""
        _atomic_write_json(
            self.directory / f"audit-{self.host}-{int(step)}.json",
            {"host": self.host, "step": int(step), **fp})
        self._own_audits[int(step)] = fp
        return self._compare_pending()

    def _compare_pending(self) -> dict | None:
        for step in sorted(self._own_audits):
            if step in self._audits_compared:
                continue
            fps = {self.host: self._own_audits[step]}
            for h in range(self.nhosts):
                if h == self.host:
                    continue
                rec = _read_json(
                    self.directory / f"audit-{h}-{step}.json")
                if rec is None:
                    return None  # compare strictly in step order
                fps[h] = rec
            self._audits_compared.add(step)
            if len({f["digest"] for f in fps.values()}) > 1:
                return {"step": step, "fps": fps}
        return None

    def final_audit_check(self, *, timeout_s: float = 10.0
                          ) -> dict | None:
        """Bounded end-of-run sweep: wait for peers' outstanding audit
        files so a divergence published at the very last audit step is
        still caught before this host exits cleanly. Timeout degrades
        to no-verdict (a dead peer is the liveness ledger's problem,
        not the audit's)."""
        deadline = time.monotonic() + timeout_s
        while True:
            div = self._compare_pending()
            if div is not None:
                return div
            if set(self._own_audits) <= self._audits_compared:
                return None  # everything compared clean
            if time.monotonic() >= deadline:
                return None
            self.beat(0, status="audit")
            time.sleep(0.05)

    def write_divergence(self, div: dict) -> None:
        """First-writer-wins divergence marker — the supervisor's
        signal that this generation ended in an SDC, with the per-host
        fingerprints attribution starts from. The black box dumps
        FIRST: the supervisor tears the generation down (SIGKILL) the
        moment it sees the marker, so the last-K-steps record must hit
        disk before the marker does."""
        flight_dump("sdc-divergence")
        _create_once_json(self.directory / "sdc-divergence.json",
                          {"by": self.host, **div,
                           "fps": {str(h): fp
                                   for h, fp in div["fps"].items()}})

    def write_trip(self, step: int, key: str, value: float,
                   z: float) -> None:
        """Self-identified sentinel trip marker: the host caught its
        OWN state misbehaving, so attribution needs no bisection. Black
        box first, marker second (the marker triggers teardown)."""
        flight_dump("sentinel-trip")
        _atomic_write_json(
            self.directory / f"sdc-trip-{self.host}.json",
            {"host": self.host, "step": int(step), "key": key,
             "value": float(value), "z": float(z)})


class HostLedger:
    """Supervisor-side view of the heartbeat files + the obs gauges
    (``cluster_host_alive`` / ``cluster_step_lag``)."""

    def __init__(self, directory: str | Path, nhosts: int, *,
                 registry=None):
        self.directory = Path(directory)
        self.nhosts = int(nhosts)
        reg = registry if registry is not None else default_registry()
        self._g_alive = reg.gauge("cluster_host_alive")
        self._g_lag = reg.gauge("cluster_step_lag")

    def read(self) -> dict[int, dict]:
        out = {}
        for h in range(self.nhosts):
            hb = _read_json(self.directory / f"hb-{h}.json")
            if hb is not None:
                out[h] = hb
        return out

    def publish(self, now: float | None = None, *,
                fresh_s: float = 5.0) -> dict[int, dict]:
        """Read + update the gauges; returns the heartbeat map with an
        ``age`` field added."""
        now = time.time() if now is None else now
        hb = self.read()
        for r in hb.values():
            r["age"] = now - r.get("time", 0.0)
        fresh = [r for r in hb.values() if r["age"] <= fresh_s]
        self._g_alive.set(float(len(fresh)))
        steps = [r.get("step", 0) for r in hb.values()]
        self._g_lag.set(float(max(steps) - min(steps)) if steps else 0.0)
        return hb

    def max_step(self) -> int:
        steps = [r.get("step", 0) for r in self.read().values()]
        return max(steps) if steps else 0


def select_resume_epoch(ckpt_dir: str | Path, *, log=print) -> int | None:
    """The degraded-resume decision (supervisor, single process, no
    Orbax): newest epoch whose integrity manifest verifies, corrupt
    epochs quarantined on the way past — "the newest commonly-verified
    epoch" every relaunched host will then restore identically."""
    from deepvision_tpu.train.manifest import newest_verified_epoch

    ckpt_dir = Path(ckpt_dir)
    if not ckpt_dir.exists():
        return None
    return newest_verified_epoch(ckpt_dir, quarantine=True, log=log)


class ClusterSupervisor:
    """Parent of a ``--supervise N`` run: spawn, watch, recover.

    ``worker_cmd(ctx) -> argv`` builds one worker's command line; the
    default launches ``train_dist.py`` in worker mode. ``ctx`` carries
    ``gen / hosts / index / host / port / resume / cluster_dir``.
    Tests inject stub workers (no jax) to exercise supervision fast.
    """

    def __init__(self, train_argv: list[str], num_hosts: int,
                 workdir: str | Path, *,
                 launcher: str | Path | None = None,
                 platform: str | None = None,
                 injector=None,
                 init_timeout_s: float = 300.0,
                 heartbeat_timeout_s: float = 120.0,
                 straggler_after_s: float = 5.0,
                 poll_s: float = 0.25,
                 max_relaunches: int = 3,
                 barrier_lead: int = BARRIER_LEAD,
                 barrier_timeout_s: float = 30.0,
                 replay_timeout_s: float = 900.0,
                 env: dict | None = None,
                 worker_cmd=None,
                 registry=None,
                 log=print):
        if num_hosts < 1:
            raise ValueError(f"need at least 1 host, got {num_hosts}")
        self.train_argv = list(train_argv)
        self.num_hosts = int(num_hosts)
        self.workdir = Path(workdir)
        self.launcher = Path(
            launcher if launcher is not None
            else Path(__file__).resolve().parents[2] / "train_dist.py")
        self.platform = platform
        self.injector = injector
        self.init_timeout_s = float(init_timeout_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.straggler_after_s = float(straggler_after_s)
        self.poll_s = float(poll_s)
        self.max_relaunches = int(max_relaunches)
        self.barrier_lead = int(barrier_lead)
        self.barrier_timeout_s = float(barrier_timeout_s)
        self.env = dict(env or {})
        self._worker_cmd = worker_cmd or self._default_worker_cmd
        self.log = log
        reg = registry if registry is not None else default_registry()
        self._registry = reg
        self._c = {k: reg.counter(f"cluster_{k}")
                   for k in ("preemptions", "resumes", "stragglers",
                             "host_deaths")}
        # silent-failure defense (resilience/sentinel.py): SDC audit /
        # quarantine counters, surfaced on --metrics-port and in the
        # grep-stable `[sentinel] trips=... ` exit line
        self._s = {k: reg.counter(f"sentinel_{k}")
                   for k in ("trips", "audits", "divergences",
                             "quarantined")}
        self.replay_timeout_s = float(replay_timeout_s)
        self._replay_n = 0
        self._scanned_dirs: set[Path] = set()
        self.cluster_root = self.workdir / "cluster"
        self.excluded_ledger = self.workdir / "excluded_hosts.json"
        # the live generation's coordination dir — where the federated
        # /metrics scrape finds the members' metrics-<index>.json dumps
        self._live_dir: Path | None = None

    # -- worker launching ------------------------------------------------
    def _default_worker_cmd(self, ctx: dict) -> list[str]:
        cmd = [sys.executable, "-u", str(self.launcher),
               "--coordinator", f"127.0.0.1:{ctx['port']}",
               "--num-processes", str(len(ctx["hosts"])),
               "--process-id", str(ctx["index"]),
               "--init-timeout-s", str(self.init_timeout_s)]
        if self.platform:
            cmd += ["--platform", self.platform]
        cmd += self.train_argv
        if ctx["resume"] and "--resume" not in self.train_argv:
            cmd += ["--resume"]
        return cmd

    def _spawn(self, gen_dir: Path, hosts: list[int], resume: bool,
               extra_env: dict | None = None
               ) -> dict[int, subprocess.Popen]:
        port = _free_port()
        procs: dict[int, subprocess.Popen] = {}
        for index, host in enumerate(hosts):
            ctx = {"gen_dir": gen_dir, "hosts": hosts, "index": index,
                   "host": host, "port": port, "resume": resume,
                   "cluster_dir": gen_dir}
            env = {**os.environ, **self.env,
                   ENV_DIR: str(gen_dir),
                   ENV_HOST: str(index),
                   ENV_NHOSTS: str(len(hosts)),
                   ENV_ORIG_HOST: str(host),
                   ENV_LEAD: str(self.barrier_lead),
                   ENV_TIMEOUT: str(self.barrier_timeout_s),
                   # fleet observability: workers stamp spans with
                   # (host, generation) and spool them into the gen dir
                   # — the crash-safe on-disk ring that survives even a
                   # SIGKILL, and the raw material of trace_merge
                   ENV_GEN: gen_dir.name,
                   "DVTPU_TRACE_SPOOL": str(gen_dir),
                   **(extra_env or {})}
            p = subprocess.Popen(
                self._worker_cmd(ctx), env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True)
            threading.Thread(
                target=self._forward, args=(index, p.stdout),
                daemon=True).start()
            procs[index] = p
        return procs

    def _forward(self, index: int, pipe) -> None:
        for line in pipe:
            self.log(f"[host {index}] {line.rstrip()}", flush=True)

    # -- chaos delivery --------------------------------------------------
    def _victim(self, procs, skip=()) -> int | None:
        """Deterministic target: the highest-index live worker not in
        ``skip`` (keeps host/index 0, the clear-rendezvous leader,
        standing as long as possible)."""
        for index in sorted(procs, reverse=True):
            if index not in skip and procs[index].poll() is None:
                return index
        return None

    def _consult_faults(self, procs, last_step: int, cur_step: int,
                        preempt_pending: set) -> int:
        """One deterministic consult per observed cluster-step VALUE
        (steps advance 1,2,3,... regardless of poll timing), so
        ``host_preempt@N`` / ``host_stall@N`` replay identically."""
        if self.injector is None:
            return cur_step
        for _ in range(last_step + 1, cur_step + 1):
            if self.injector.check_host_preempt():
                v = self._victim(procs, skip=preempt_pending)
                if v is not None:
                    self.log(f"[cluster] delivering preemption notice "
                             f"(SIGTERM) to host index {v}", flush=True)
                    preempt_pending.add(v)
                    self._c["preemptions"].inc()
                    procs[v].send_signal(signal.SIGTERM)
            stall = self.injector.check_host_stall()
            if stall is not None:
                v = self._victim(procs, skip=preempt_pending)
                if v is not None:
                    self.log(f"[cluster] SIGSTOPping host index {v} "
                             f"for {stall:.1f}s", flush=True)
                    procs[v].send_signal(signal.SIGSTOP)
                    t = threading.Timer(
                        stall, lambda p=procs[v]: p.poll() is None
                        and p.send_signal(signal.SIGCONT))
                    t.daemon = True
                    t.start()
        return cur_step

    # -- one generation --------------------------------------------------
    def _run_generation(self, gen: int, hosts: list[int],
                        resume: bool) -> tuple[str, set]:
        gen_dir = self.cluster_root / f"gen-{gen:03d}"
        gen_dir.mkdir(parents=True, exist_ok=True)
        self._live_dir = gen_dir
        self.log(f"[cluster] gen {gen}: launching hosts {hosts} "
                 f"(resume={resume})", flush=True)
        procs = self._spawn(gen_dir, hosts, resume)
        ledger = HostLedger(gen_dir, len(hosts),
                            registry=self._registry)
        preempt_pending: set[int] = set()
        straggling: set[int] = set()
        seen_beat: set[int] = set()
        last_step = 0
        start = time.monotonic()
        dead: set[int] = set()
        sdc_seen = False
        while any(p.poll() is None for p in procs.values()):
            time.sleep(self.poll_s)
            now = time.time()
            hb = ledger.publish(now, fresh_s=self.straggler_after_s)
            if not sdc_seen and (
                    (gen_dir / "sdc-divergence.json").exists()
                    or any(True for _ in gen_dir.glob(
                        "sdc-trip-*.json"))):
                # an SDC verdict is out: the detecting host exits 76
                # and every peer's next collective would wedge on its
                # missing dispatches — tear the generation down NOW
                # and move to attribution
                sdc_seen = True
                self.log("[cluster] SDC verdict published; tearing "
                         "down the generation for attribution",
                         flush=True)
                for q in procs.values():
                    if q.poll() is None:
                        q.kill()
                continue
            last_step = self._consult_faults(
                procs, last_step,
                max([r.get("step", 0) for r in hb.values()], default=0),
                preempt_pending)
            for index, p in procs.items():
                if p.poll() is not None or index in dead:
                    continue
                rec = hb.get(index)
                # hosts that never beat yet are still importing/compiling
                # — the init timeout bounds that phase, not this ledger
                if rec is None:
                    if index not in seen_beat and (
                            time.monotonic() - start
                            > self.heartbeat_timeout_s * 4):
                        rec = {"age": float("inf")}
                    else:
                        continue
                seen_beat.add(index)
                age = rec["age"]
                if age > self.heartbeat_timeout_s:
                    self.log(f"[cluster] host index {index} heartbeat "
                             f"dead ({age:.0f}s > "
                             f"{self.heartbeat_timeout_s:.0f}s); killing "
                             "the generation for a supervised relaunch",
                             flush=True)
                    dead.add(index)
                    self._c["host_deaths"].inc()
                    for q in procs.values():
                        if q.poll() is None:
                            q.kill()
                elif age > self.straggler_after_s:
                    if index not in straggling:
                        straggling.add(index)
                        self._c["stragglers"].inc()
                        self.log(f"[cluster] straggler host index "
                                 f"{index}: no heartbeat in {age:.1f}s "
                                 f"(budget {self.straggler_after_s:.1f}s"
                                 "); watching", flush=True)
                else:
                    straggling.discard(index)
        for p in procs.values():
            p.wait()
        codes = {i: p.returncode for i, p in procs.items()}
        self.log(f"[cluster] gen {gen} exit codes: {codes}", flush=True)
        removed = {hosts[i] for i in preempt_pending}
        self._scan_sentinel(gen_dir)
        if (gen_dir / "sdc-divergence.json").exists() \
                or list(gen_dir.glob("sdc-trip-*.json")):
            # an SDC verdict outranks every other classification: a
            # peer that ALSO went heartbeat-silent was almost certainly
            # wedged on the detector's abandoned collectives
            return "sdc", removed
        if dead:
            return "dead", removed
        if all(c == 0 for c in codes.values()):
            return "done", removed
        if all(c in (0, 143) for c in codes.values()):
            commits = ClusterMember(gen_dir, 0, len(hosts)
                                    ).commit_records()
            if len(commits) == len(hosts) and len(
                    {(c["epoch"], c["step"]) for c in commits}) == 1:
                c = commits[0]
                self.log(f"[cluster] coordinated save committed by all "
                         f"{len(hosts)} hosts at epoch {c['epoch']} "
                         f"step {c['step']}", flush=True)
            else:
                self.log("[cluster] preempted without a mid-epoch "
                         "coordinated save (epoch-boundary exit, or "
                         "degraded barrier); resume falls back to the "
                         "newest commonly-verified epoch checkpoint",
                         flush=True)
            return "preempted", removed
        return "crashed", removed

    # -- checkpoint selection for degraded relaunches --------------------
    def _ckpt_dir(self) -> Path | None:
        model = argv_value(self.train_argv, "-m", "--model")
        if model is None:
            return None
        return self.workdir / model / "ckpt"

    def _degraded_cleanup(self) -> None:
        d = self._ckpt_dir()
        if d is None or not d.exists():
            return
        epoch = select_resume_epoch(d, log=self.log)
        self.log(f"[cluster] newest commonly-verified epoch: {epoch}",
                 flush=True)

    def _has_checkpoint(self) -> bool:
        d = self._ckpt_dir()
        if d is None:
            return False
        from deepvision_tpu.train.manifest import fs_epochs

        if fs_epochs(d):
            return True
        for sub in ("ckpt_preempt", "ckpt_preempt_unlocked"):
            if fs_epochs(d.parent / sub):
                return True
        return False

    # -- federated metrics (obs/distributed.py) --------------------------
    def render_federated_metrics(self) -> str:
        """The ``--metrics-port`` text: the supervisor's own registry
        (cluster_*/sentinel_* counters and liveness gauges) plus every
        live host's registry dump — published on the heartbeat cadence
        as ``metrics-<index>.json`` in the generation dir — labelled
        ``{host="<orig id>"}`` with exact counter sums, so one scrape
        of the supervisor describes the whole training fleet."""
        from deepvision_tpu.obs.distributed import render_federated

        children: dict[str, dict] = {}
        d = self._live_dir
        if d is not None and d.exists():
            for f in sorted(d.glob("metrics-*.json")):
                rec = _read_json(f)
                if rec and isinstance(rec.get("dump"), dict):
                    children[str(rec.get("host", f.stem.split("-")[-1]))] \
                        = rec["dump"]
        return render_federated(children, own=self._registry,
                                label="host", own_label="supervisor")

    # -- SDC attribution: replay bisection + quarantine ------------------
    def _extract_black_box(self, gen_dir: Path, host: int) -> Path | None:
        """A SIGKILLed culprit ran no dump handler — its crash-safe
        span spool tail and last published metrics dump ARE the black
        box. Extract them into a flight-recorder-format file in the
        workdir, so every quarantine verdict ships with the culprit's
        last K steps (``tools/trace_merge.py`` renders it like any
        other dump)."""
        from deepvision_tpu.obs.distributed import read_spool, spool_paths

        try:
            events: list[dict] = []
            for p in spool_paths(gen_dir):
                if f"-host{host}-" in p.name:
                    events.extend(read_spool(p)["events"])
            events.sort(key=lambda e: e.get("wall", 0.0))
            tail = events[-512:]
            for e in tail:
                # spool events carry calibrated wall stamps; rebase the
                # dump on epoch_wall=0 so wall == ts for the merger
                e["ts"] = e.pop("wall", e.get("ts", 0.0))
                e.setdefault("kind", "span")
            metrics = None
            for f in gen_dir.glob("metrics-*.json"):
                rec = _read_json(f)
                if rec and rec.get("host") == host:
                    metrics = rec
            out = self.workdir / f"flightrec-host{host}-quarantine.json"
            _atomic_write_json(out, {
                "flightrec": 1, "reason": "quarantine",
                "time": time.time(), "pid": None,
                "labels": {"host": host, "role": f"host{host}"},
                "epoch_wall": 0.0,
                "events": tail,
                "snapshot": (metrics or {}).get("dump"),
            })
            self.log(f"[sentinel] black box for quarantined host {host} "
                     f"({len(tail)} events from its spool): {out}",
                     flush=True)
            return out
        except Exception as e:
            self.log(f"[sentinel] black-box extraction for host {host} "
                     f"failed: {type(e).__name__}: {e}", flush=True)
            return None

    def _scan_sentinel(self, d: Path) -> None:
        """Fold one generation/replay dir's sentinel artifacts into the
        counters (idempotent per directory)."""
        if d in self._scanned_dirs or not d.exists():
            return
        self._scanned_dirs.add(d)
        audits = {f.name for f in d.glob("audit-*.json")}
        trips = list(d.glob("sdc-trip-*.json"))
        if audits:
            self._s["audits"].inc(len(audits))
        if trips:
            self._s["trips"].inc(len(trips))
        if (d / "sdc-divergence.json").exists():
            self._s["divergences"].inc()

    def _replay(self, probe: list[int],
                until: int) -> tuple[str, dict | None]:
        """Re-run the suspect window on the host subset ``probe`` (from
        the newest commonly-verified checkpoint, sdc injection
        quiesced) and read the verdict from its audit artifacts:

        - ``("dirty", None)``  — the replay itself tripped a sentinel
          or internally diverged (a sticky fault lives in ``probe``);
        - ``("clean", fp)``    — the subset agreed through the window;
          ``fp`` is the replayed ground-truth fingerprint at ``until``;
        - ``("failed", None)`` — no verdict (crash/timeout): treated as
          dirty by the caller, which keeps attribution conservative.
        """
        self._replay_n += 1
        rdir = self.cluster_root / f"replay-{self._replay_n:03d}"
        rdir.mkdir(parents=True, exist_ok=True)
        self.log(f"[sentinel] replay {self._replay_n}: hosts {probe} "
                 f"through run step {until} (quiesced, from the newest "
                 "verified checkpoint)", flush=True)
        self._degraded_cleanup()
        procs = self._spawn(rdir, probe, self._has_checkpoint(),
                            extra_env={ENV_REPLAY: str(until),
                                       ENV_QUIESCE: "1"})
        deadline = time.monotonic() + self.replay_timeout_s
        while any(p.poll() is None for p in procs.values()):
            if (rdir / "sdc-divergence.json").exists() \
                    or any(True for _ in rdir.glob("sdc-trip-*.json")):
                # dirty verdict: stop burning compute, the surviving
                # replay peers would wedge on dead collectives anyway
                for p in procs.values():
                    if p.poll() is None:
                        p.kill()
            if time.monotonic() >= deadline:
                self.log("[sentinel] replay timed out; killing it",
                         flush=True)
                for p in procs.values():
                    if p.poll() is None:
                        p.kill()
            time.sleep(self.poll_s)
        for p in procs.values():
            p.wait()
        self._scan_sentinel(rdir)
        if (rdir / "sdc-divergence.json").exists() \
                or list(rdir.glob("sdc-trip-*.json")):
            return "dirty", None
        fps = [_read_json(rdir / f"audit-{i}-{until}.json")
               for i in range(len(probe))]
        if any(fp is None for fp in fps):
            return "failed", None
        if len({fp["digest"] for fp in fps}) > 1:
            return "dirty", None  # internal disagreement, unmarked
        return "clean", fps[0]

    def _attribute_against(self, fps: dict[int, dict],
                           truth: dict) -> list[int]:
        """Hosts whose original audit fingerprint disagrees with the
        replayed ground truth. Exact digests first (a bit-identical
        replay — same host count — isolates the culprit exactly); when
        the replay ran on a DIFFERENT host count, reduction-order and
        low-precision rounding noise makes every digest differ, so
        attribution becomes a noise-floor ratio test: the cleanest
        host's deviation IS the replay noise (it hits every comparison
        equally), and hosts sitting ATTRIBUTION_RATIO above it carry
        direct corruption. Empty = ambiguous — quarantine nothing
        blind."""
        from deepvision_tpu.resilience.sentinel import (
            ATTRIBUTION_RATIO,
            fingerprint_deviation,
            fingerprints_agree,
        )

        exact = sorted(h for h, fp in fps.items()
                       if not fingerprints_agree(fp, truth))
        if exact and len(exact) < len(fps):
            return exact
        devs = {h: fingerprint_deviation(fp, truth)
                for h, fp in fps.items()}
        floor = min(devs.values())
        self.log("[sentinel] attribution deviations vs replayed "
                 "truth: "
                 + " ".join(f"host{h}={d:.3g}"
                            for h, d in sorted(devs.items()))
                 + f" (noise floor {floor:.3g})", flush=True)
        over = sorted(h for h, d in devs.items()
                      if d > floor * ATTRIBUTION_RATIO + 1e-12)
        if over and len(over) < len(devs):
            return over
        return []

    def _quarantine_sdc(self, gen_dir: Path,
                        hosts: list[int]) -> list[int]:
        """Attribute a detected SDC to culprit host(s) and persist the
        excluded-hosts ledger. Attribution ladder:

        1. self-identified trips (a host's own z-score caught its
           corrupted state) — no replay needed;
        2. strict fingerprint majority at the divergent audit step —
           the minority computed garbage;
        3. replay bisection: binary-search the suspect set with
           deterministic window replays (≤ ceil(log2 N) replays — a
           clean replay's fingerprint is ground truth and attributes
           everyone at once; a dirty one halves the suspects).
        """
        import math as _math

        tripped = sorted(
            hosts[rec["host"]]
            for f in gen_dir.glob("sdc-trip-*.json")
            if (rec := _read_json(f)) is not None
            and rec["host"] < len(hosts))
        if tripped:
            self._exclude(tripped, reason="self-identified sentinel "
                          "trip", replays=0, gen_dir=gen_dir)
            return tripped
        div = _read_json(gen_dir / "sdc-divergence.json")
        if div is None:
            return []
        step = int(div["step"])
        fps = {hosts[int(i)]: fp for i, fp in div["fps"].items()
               if int(i) < len(hosts)}
        by_digest: dict[str, list[int]] = {}
        for h, fp in fps.items():
            by_digest.setdefault(fp["digest"], []).append(h)
        majority = max(by_digest.values(), key=len)
        if len(majority) * 2 > len(fps):
            culprits = sorted(h for h in fps if h not in majority)
            self._exclude(culprits, reason=f"fingerprint minority at "
                          f"audit step {step}", replays=0, step=step,
                          gen_dir=gen_dir)
            return culprits
        # no majority (e.g. a 2-host fleet): replay bisection. A probe
        # that stays internally consistent yields the ground-truth
        # fingerprint (deterministic elastic replay) and attributes
        # everyone at once; a probe that trips or internally diverges
        # contains the (sticky) fault and halves the suspect set —
        # single-fault assumption, the standard bisection contract. A
        # would-be singleton probe rides with an already-exonerated
        # host so a sticky culprit still shows up as INTERNAL
        # disagreement instead of masquerading as ground truth (with
        # nobody exonerated yet — a 2-host fleet's first replay — a
        # deterministic sticky fault is formally unattributable; the
        # transient-SDC model, the common real-world case, is).
        suspects = sorted(fps)
        exonerated: list[int] = []
        budget = max(1, _math.ceil(_math.log2(max(2, len(suspects)))))
        replays = 0
        while len(suspects) > 1 and replays < budget:
            half = suspects[:(len(suspects) + 1) // 2]
            probe = (half if len(half) > 1 or not exonerated
                     else [half[0], exonerated[0]])
            verdict, truth = self._replay(probe, step)
            replays += 1
            if verdict == "failed":
                self.log("[sentinel] replay produced no verdict "
                         "(crash/timeout); aborting attribution rather "
                         "than quarantining on a broken replay",
                         flush=True)
                return []
            if verdict == "clean":
                culprits = self._attribute_against(fps, truth)
                if culprits:
                    self._exclude(culprits, reason="fingerprint "
                                  "mismatch vs replayed ground truth",
                                  replays=replays, step=step,
                                  gen_dir=gen_dir)
                    return culprits
                self.log("[sentinel] replay matched every original "
                         "fingerprint — divergence did not reproduce; "
                         "quarantining nothing", flush=True)
                return []
            # dirty: the fault is in the probed half; the other half
            # is exonerated under the single-fault assumption
            exonerated.extend(h for h in suspects if h not in half)
            suspects = half
        if len(suspects) == 1:
            self._exclude(suspects, reason="replay bisection",
                          replays=replays, step=step, gen_dir=gen_dir)
            return suspects
        self.log(f"[sentinel] attribution ambiguous after {replays} "
                 f"replays (suspects {suspects}); NOT quarantining "
                 "blind — operator intervention required", flush=True)
        return []

    def _exclude(self, culprits: list[int], *, reason: str,
                 replays: int, step: int | None = None,
                 gen_dir: Path | None = None) -> None:
        ledger = _read_json(self.excluded_ledger) or {"excluded": []}
        if gen_dir is not None:
            for h in culprits:
                self._extract_black_box(gen_dir, h)
        for h in culprits:
            ledger["excluded"].append(
                {"host": int(h), "reason": reason,
                 "replays": int(replays),
                 **({"step": int(step)} if step is not None else {}),
                 "time": time.time()})
            self._s["quarantined"].inc()
            self.log(f"[sentinel] QUARANTINED host {h} ({reason}; "
                     f"{replays} replay(s)); ledger: "
                     f"{self.excluded_ledger}", flush=True)
        _atomic_write_json(self.excluded_ledger, ledger)

    # -- the supervising loop --------------------------------------------
    def run(self) -> int:
        hosts = list(range(self.num_hosts))
        gen = 0
        relaunches_left = self.max_relaunches
        resume = False
        rc = 0
        while True:
            outcome, removed = self._run_generation(gen, hosts, resume)
            if outcome == "done":
                break
            if outcome == "preempted":
                hosts = [h for h in hosts if h not in removed]
                if not hosts:
                    self.log("[cluster] every host preempted; nothing "
                             "left to resume on", flush=True)
                    rc = 1
                    break
            elif outcome == "sdc":
                culprits = self._quarantine_sdc(
                    self.cluster_root / f"gen-{gen:03d}", hosts)
                if not culprits:
                    self.log("[cluster] SDC detected but not "
                             "attributed; refusing to continue on a "
                             "fleet with a known-corrupt member",
                             flush=True)
                    rc = 1
                    break
                # drop quarantined hosts AND any host that was already
                # holding a preemption notice when the SDC verdict
                # outranked the generation's classification — its
                # machine is leaving either way
                hosts = [h for h in hosts
                         if h not in culprits and h not in removed]
                if not hosts:
                    self.log("[cluster] every host quarantined; "
                             "nothing trustworthy left to resume on",
                             flush=True)
                    rc = 1
                    break
            else:  # crashed / heartbeat-dead
                if relaunches_left <= 0:
                    self.log("[cluster] relaunch budget exhausted; "
                             "giving up", flush=True)
                    rc = 1
                    break
                relaunches_left -= 1
                self._degraded_cleanup()
            self._c["resumes"].inc()
            resume = self._has_checkpoint()
            gen += 1
        self.log(
            "[cluster] "
            + " ".join(f"{k}={c.value}" for k, c in self._c.items())
            + f" hosts={len(hosts)}/{self.num_hosts} generations={gen + 1}",
            flush=True)
        # grep-stable silent-failure summary (zeros when sentinels are
        # off — the line's PRESENCE is part of the exit contract)
        self.log(
            "[sentinel] "
            + " ".join(f"{k}={c.value}" for k, c in self._s.items()),
            flush=True)
        return rc
