"""Deterministic fault injection for chaos tests (and drills).

A :class:`FaultInjector` holds a parsed schedule of :class:`FaultSpec`s
and a per-site consultation counter. Each layer that can fail consults
its site hook at a well-defined point; the injector fires a spec when
the site's occurrence counter enters the spec's window. Because the
counters advance one per consultation and every consumer consults at a
deterministic program point, a schedule replays bit-identically on CPU
— the property the chaos matrix in ``tests/test_resilience.py`` leans
on. Probabilistic specs (``kind~p``) draw from a seeded generator
instead, for soak-style drills.

Schedule grammar (comma-separated specs)::

    kind@AT[xTIMES][:ARG]     fire at site occurrences [AT, AT+TIMES)
    kind~PROB[:ARG]           fire with probability PROB per consult

Sites and their consultation points:

==================  =====================================================
``nan_step``        per train batch yielded to the feed (Trainer); fires
                    by NaN-poisoning the batch so the checkify tripwire
                    raises inside the compiled step. Aliases: ``nan``,
                    ``nan_grad``.
``data_io``         per upstream pull in the prefetch producer
                    (``data/prefetch.py``) and per record read in
                    ``data/tfrecord.read_records``; fires by raising
                    :class:`InjectedIOError`. Alias: ``io``.
``ckpt_corrupt``    per committed checkpoint save
                    (``train/checkpoint.py``); fires by garbling the
                    largest file of the just-saved epoch on disk.
                    Alias: ``ckpt``.
``stall``           per train batch yielded to the feed; fires by
                    sleeping ``ARG`` seconds (default 1.0) — trips the
                    stall watchdog.
``dispatch_crash``  per dispatched serve batch (``serve/engine.py``);
                    fires by raising :class:`InjectedCrash` in the
                    dispatcher loop body. Alias: ``crash``.
``replica_kill``    per routed request attempt (``serve/router.py``);
                    fires by hard-killing the chosen replica (SIGKILL
                    for process replicas) BEFORE the attempt is sent,
                    so the router's dead-replica failover path runs.
                    Alias: ``rkill``.
``replica_slow``    per routed request attempt; fires by injecting
                    ``ARG`` seconds of extra attempt latency (default
                    0.5) — exercises hedged retries. Alias: ``rslow``.
``host_preempt``    per observed cluster step in the multi-host
                    supervisor (``resilience/cluster.py``); fires by
                    delivering the preemption notice (SIGTERM) to one
                    live host — the coordinated save barrier + elastic
                    resume path runs. Alias: ``preempt``.
``host_stall``      per observed cluster step in the supervisor; fires
                    by SIGSTOPping one live host for ``ARG`` seconds
                    (default 2.0) — trips the straggler detector.
                    Alias: ``hstall``.
``worker_kill``     per merged batch in the multi-process host loader
                    (``data/loader.py``); fires by SIGKILLing the
                    decode worker whose turn it is — the bounded
                    respawn-at-shard-position path runs.
                    Alias: ``wkill``.
``sdc_grad``        silent data corruption of the update: at RUN step
                    AT (epoch-anchored ``epoch*steps_per_epoch+step``,
                    NOT a consult counter — so a resumed or replayed
                    window re-fires at the same point bit-identically)
                    the Trainer scales one parameter leaf of THIS
                    host's replica by ``:ARG`` (default the silent
                    ``sentinel.SDC_GRAD_SCALE``); ``:hostH`` instead
                    targets original cluster host H only. Detected by
                    the sentinel z-score (loud scales) or the
                    cross-host agreement audit (silent scales).
                    Alias: ``sdc``.
``sdc_param``       silent single-bit corruption: at RUN step AT, XOR
                    the low mantissa bit of one element of one
                    parameter leaf on the targeted host — the one-ulp
                    SDC only the fingerprint audit can see.
                    Alias: ``sdcp``.
``session_kill``    per committed session-state update in the
                    ``serve/sessions.py`` SessionStore; fires by
                    dropping that session's device-resident state
                    (snapshots on disk are kept), so the next frame
                    exercises the snapshot-restore path in-process.
                    Alias: ``sesskill``.
``snapshot_corrupt``  per committed session snapshot; fires by garbling
                    the just-written snapshot file on disk, so restore
                    must fall back to the previous snapshot or declare
                    an honest ``state_reset``. Alias: ``snapcorrupt``.
==================  =====================================================

The sdc sites accept ``:hostH`` (e.g. ``sdc_grad@20:host1``) in the
ARG slot: the spec then fires only in the process whose ORIGINAL
cluster host id (``FaultInjector(host=...)``, exported by the
supervisor as ``DVTPU_CLUSTER_ORIG_HOST``) matches — host ids are
stable across elastic relaunches, so a quarantined host's fault can
never follow the job onto a survivor. ``FaultInjector(sdc_quiesce=
True)`` (supervisor replay generations) disarms the sdc sites: the
replay models re-running the window on hardware that is not
misbehaving, which is what makes the replayed fingerprint the ground
truth the bisection attributes against.

Example: ``"nan@14,ckpt@1,io@8x2"`` — NaN-poison the 15th train batch,
corrupt the 2nd checkpoint save, and fail the 9th and 10th data pulls
with transient read errors.
"""

from __future__ import annotations

import re
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

import numpy as np

__all__ = [
    "FaultSpec",
    "FaultInjector",
    "InjectedIOError",
    "InjectedCrash",
    "parse_schedule",
    "format_spec",
    "split_schedule",
    "poison_batch",
]

# canonical site names + accepted aliases
SITES = ("nan_step", "data_io", "ckpt_corrupt", "stall", "dispatch_crash",
         "replica_kill", "replica_slow", "host_preempt", "host_stall",
         "worker_kill", "sdc_grad", "sdc_param", "session_kill",
         "snapshot_corrupt")
# the sites the CLUSTER SUPERVISOR consults (resilience/cluster.py);
# train_dist.py splits a mixed schedule on this set so supervisor-level
# specs never reach the in-job injector (and vice versa)
CLUSTER_SITES = ("host_preempt", "host_stall")
# RUN-step-keyed sites (fired by step VALUE, not consult occurrence):
# deterministic under resume/replay from any point, the property the
# supervisor's replay bisection leans on
SDC_SITES = ("sdc_grad", "sdc_param")
_ALIASES = {
    "nan": "nan_step", "nan_grad": "nan_step",
    "io": "data_io",
    "ckpt": "ckpt_corrupt",
    "crash": "dispatch_crash",
    "rkill": "replica_kill",
    "rslow": "replica_slow",
    "preempt": "host_preempt",
    "hstall": "host_stall",
    "wkill": "worker_kill",
    "sdc": "sdc_grad",
    "sdcp": "sdc_param",
    "sesskill": "session_kill",
    "snapcorrupt": "snapshot_corrupt",
}
_HOST_ARG = re.compile(r"^host(\d+)$")


class InjectedIOError(IOError):
    """A scheduled transient data-read failure (retryable)."""


class InjectedCrash(RuntimeError):
    """A scheduled unexpected dispatcher/loop crash."""


@dataclass
class FaultSpec:
    """One scheduled fault: fires at site occurrences
    ``[at, at + times)`` — or, when ``prob`` is set, with probability
    ``prob`` on every consult. ``arg`` carries a per-kind parameter
    (stall duration in seconds)."""

    kind: str
    at: int | None = None
    times: int = 1
    prob: float | None = None
    arg: float | None = None
    host: int | None = None
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        self.kind = _ALIASES.get(self.kind, self.kind)
        if self.kind not in SITES:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; expected one of "
                f"{SITES} (aliases {sorted(_ALIASES)})")
        if (self.at is None) == (self.prob is None):
            raise ValueError(
                f"{self.kind}: exactly one of at= / prob= required")
        if self.prob is not None and not 0.0 <= self.prob <= 1.0:
            raise ValueError(f"{self.kind}: prob must be in [0,1], "
                             f"got {self.prob}")
        if self.times < 1:
            raise ValueError(f"{self.kind}: times must be >= 1, "
                             f"got {self.times}")
        if self.host is not None and self.kind not in SDC_SITES:
            raise ValueError(
                f"{self.kind}: ':hostH' targeting only applies to the "
                f"sdc sites {SDC_SITES}")
        if self.kind in SDC_SITES and self.prob is not None:
            # step-keyed sites are replay-deterministic BY DEFINITION;
            # a probabilistic draw per observed step would break the
            # bisection's ground-truth contract
            raise ValueError(
                f"{self.kind}: sdc sites are run-step-keyed "
                "(kind@STEP only; kind~PROB is not replayable)")

    def should_fire(self, occurrence: int, rng) -> bool:
        if self.prob is not None:
            return bool(rng.random() < self.prob)
        return self.at <= occurrence < self.at + self.times


def parse_schedule(spec: str) -> list[FaultSpec]:
    """Parse the schedule grammar (module docstring) into specs."""
    out: list[FaultSpec] = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        arg = host = None
        if ":" in raw:
            raw, _, argtok = raw.partition(":")
            m = _HOST_ARG.match(argtok.strip())
            if m:  # sdc host targeting: sdc_grad@20:host1
                host = int(m.group(1))
            else:
                try:
                    arg = float(argtok)
                except ValueError:
                    raise ValueError(
                        f"fault spec {raw!r}: bad :ARG value {argtok!r}"
                        " (want a float, or hostH for the sdc sites)")
        if "@" in raw:
            kind, _, attok = raw.partition("@")
            times = 1
            if "x" in attok:
                attok, _, timestok = attok.partition("x")
                times = _parse_int(timestok, raw, "xTIMES")
            out.append(FaultSpec(kind=kind.strip(),
                                 at=_parse_int(attok, raw, "@AT"),
                                 times=times, arg=arg, host=host))
        elif "~" in raw:
            kind, _, ptok = raw.partition("~")
            try:
                prob = float(ptok)
            except ValueError:
                raise ValueError(f"fault spec {raw!r}: bad ~PROB "
                                 f"value {ptok!r}") from None
            out.append(FaultSpec(kind=kind.strip(), prob=prob, arg=arg,
                                 host=host))
        else:
            raise ValueError(
                f"fault spec {raw!r}: expected kind@AT[xN][:ARG] "
                "or kind~PROB[:ARG]")
    return out


def format_spec(spec: FaultSpec) -> str:
    """Inverse of :func:`parse_schedule` for one spec (canonical kind
    names; round-trips through the grammar)."""
    if spec.prob is not None:
        s = f"{spec.kind}~{spec.prob:g}"
    else:
        s = f"{spec.kind}@{spec.at}"
        if spec.times > 1:
            s += f"x{spec.times}"
    if spec.host is not None:
        s += f":host{spec.host}"
    elif spec.arg is not None:
        s += f":{spec.arg:g}"
    return s


def split_schedule(schedule: str, kinds) -> tuple[str, str]:
    """Partition a schedule string into (specs whose kind is in
    ``kinds``, the rest), both re-serialized through the grammar —
    how ``train_dist.py`` routes cluster-level sites to the supervisor
    and everything else to the in-job injectors."""
    kinds = set(kinds)
    mine, rest = [], []
    for spec in parse_schedule(schedule):
        (mine if spec.kind in kinds else rest).append(format_spec(spec))
    return ",".join(mine), ",".join(rest)


def _parse_int(tok: str, raw: str, what: str) -> int:
    try:
        return int(tok)
    except ValueError:
        raise ValueError(
            f"fault spec {raw!r}: bad {what} value {tok!r}") from None


def poison_batch(batch: dict) -> dict:
    """NaN-fill the first float array of ``batch`` (a shallow COPY —
    synthetic datasets yield views of one resident array, and an
    in-place write would poison every later epoch too)."""
    out = dict(batch)
    for k, v in out.items():
        arr = np.asarray(v)
        if np.issubdtype(arr.dtype, np.floating):
            out[k] = np.full_like(arr, np.nan)
            return out
    # integer-only batch (uint8 wire formats): poison via float cast so
    # the step's normalization still produces NaN activations
    k = next(iter(out))
    out[k] = np.full(np.asarray(out[k]).shape, np.nan, np.float32)
    return out


class FaultInjector:
    """Thread-safe, occurrence-counted fault oracle.

    ``schedule`` is a grammar string or an iterable of
    :class:`FaultSpec`. Each site hook below increments that site's
    counter once per consultation and fires any spec whose window the
    counter entered; fired faults are recorded (``fired`` /
    :meth:`summary`) so tests and logs can assert exactly what was
    injected. Counters are monotonic across rollbacks/retries — a
    consumed occurrence never re-fires, which is what makes "inject one
    NaN step, recover, converge" a well-posed test.
    """

    def __init__(self, schedule: str | list[FaultSpec] | None,
                 *, seed: int = 0, host: int | None = None,
                 sdc_quiesce: bool = False):
        if isinstance(schedule, str):
            schedule = parse_schedule(schedule)
        self.specs: list[FaultSpec] = list(schedule or [])
        self._rng = np.random.default_rng(seed)
        self._counts: dict[str, int] = {s: 0 for s in SITES}
        self._lock = threading.Lock()
        self.fired: list[tuple[str, int]] = []  # (site, occurrence/step)
        # this process's ORIGINAL cluster host id (stable across
        # elastic relaunches) for ':hostH'-targeted sdc specs; None =
        # single-host / untargeted
        self.host = host
        # replay generations run with the sdc sites disarmed: the
        # replayed window is the bisection's ground truth
        self.sdc_quiesce = bool(sdc_quiesce)
        self._sdc_fired: set[tuple[str, int]] = set()

    def _consult(self, site: str) -> FaultSpec | None:
        """Advance ``site``'s counter; return the spec to fire, if any."""
        with self._lock:
            occ = self._counts[site]
            self._counts[site] = occ + 1
            for spec in self.specs:
                if spec.kind == site and spec.should_fire(occ, self._rng):
                    spec.fired += 1
                    self.fired.append((site, occ))
                    return spec
        return None

    # -- site hooks ------------------------------------------------------
    def poison_nan(self, batch: dict) -> tuple[dict, bool]:
        """Trainer hook, per yielded train batch: -> (batch, fired)."""
        spec = self._consult("nan_step")
        if spec is None:
            return batch, False
        return poison_batch(batch), True

    def check_io(self, what: str = "data read") -> None:
        """Data-layer hook: raise a transient read error when scheduled."""
        spec = self._consult("data_io")
        if spec is not None:
            raise InjectedIOError(
                f"injected transient {what} failure "
                f"(occurrence {self._counts['data_io'] - 1})")

    def maybe_stall(self, *, sleep=time.sleep) -> bool:
        """Trainer hook: sleep through a scheduled stall (watchdog food)."""
        spec = self._consult("stall")
        if spec is None:
            return False
        sleep(spec.arg if spec.arg is not None else 1.0)
        return True

    def check_dispatch(self) -> None:
        """Serve hook, per dispatched batch: crash the loop body when
        scheduled."""
        spec = self._consult("dispatch_crash")
        if spec is not None:
            raise InjectedCrash(
                "injected dispatcher crash "
                f"(occurrence {self._counts['dispatch_crash'] - 1})")

    def check_replica_kill(self) -> bool:
        """Router hook, per routed request attempt: True when the chosen
        replica should be hard-killed before the attempt is sent (the
        router then exercises its real dead-replica failover path)."""
        return self._consult("replica_kill") is not None

    def check_replica_slow(self) -> float | None:
        """Router hook, per routed request attempt: extra attempt
        latency in seconds (``:ARG``, default 0.5) when scheduled, else
        None — slow enough attempts trip the router's hedged retry."""
        spec = self._consult("replica_slow")
        if spec is None:
            return None
        return spec.arg if spec.arg is not None else 0.5

    def check_host_preempt(self) -> bool:
        """Cluster-supervisor hook, per observed cluster step: True when
        the preemption notice (SIGTERM) should be delivered to one live
        host — the coordinated save barrier then runs in-job."""
        return self._consult("host_preempt") is not None

    def check_host_stall(self) -> float | None:
        """Cluster-supervisor hook, per observed cluster step: seconds
        to SIGSTOP one live host (``:ARG``, default 2.0) when scheduled,
        else None — straggler-detector food."""
        spec = self._consult("host_stall")
        if spec is None:
            return None
        return spec.arg if spec.arg is not None else 2.0

    def check_worker_kill(self) -> bool:
        """Loader hook, per merged batch: True when the decode worker
        whose turn it is should be SIGKILLed before the pull (the
        bounded respawn path then runs)."""
        return self._consult("worker_kill") is not None

    def check_sdc(self, run_step: int) -> FaultSpec | None:
        """Trainer hook, once per optimizer step: the sdc spec to
        apply at this RUN step, if any. Unlike the occurrence-counted
        sites, sdc specs fire by step VALUE — a resumed or replayed
        window covering the step re-fires identically, which is what
        lets the supervisor's bisection treat replays as ground truth
        (with ``sdc_quiesce`` disarming the injection there). A
        ``:hostH`` target fires only when it names this injector's
        original host; each (site, step) fires at most once per
        process."""
        if self.sdc_quiesce:
            return None
        with self._lock:
            for spec in self.specs:
                if spec.kind not in SDC_SITES:
                    continue
                if not spec.at <= run_step < spec.at + spec.times:
                    continue
                if spec.host is not None and spec.host != self.host:
                    continue
                key = (spec.kind, int(run_step))
                if key in self._sdc_fired:
                    continue
                self._sdc_fired.add(key)
                spec.fired += 1
                self.fired.append(key)
                return spec
        return None

    def check_session_kill(self) -> bool:
        """SessionStore hook, per committed session-state update: True
        when that session's device-resident state should be dropped
        (snapshots kept) so the next frame runs the restore path."""
        return self._consult("session_kill") is not None

    def corrupt_snapshot(self, path: str | Path) -> bool:
        """SessionStore hook, per committed session snapshot: garble the
        just-written snapshot file so restore must fall back to the
        previous snapshot or declare an honest ``state_reset``."""
        spec = self._consult("snapshot_corrupt")
        if spec is None:
            return False
        Path(path).write_bytes(b"\x00injected-snapshot-corruption\x00")
        print(f"[fault] corrupted session snapshot {path}", flush=True)
        return True

    def corrupt_checkpoint(self, step_dir: str | Path) -> bool:
        """Checkpoint hook, per committed save: garble the largest file
        under ``step_dir`` (the array payload — guarantees both a
        checksum mismatch and, without verification, a restore crash)."""
        spec = self._consult("ckpt_corrupt")
        if spec is None:
            return False
        step_dir = Path(step_dir)
        files = sorted((p for p in step_dir.rglob("*") if p.is_file()),
                       key=lambda p: (p.stat().st_size, str(p)))
        if not files:
            return False
        victim = files[-1]
        victim.write_bytes(b"\x00injected-corruption\x00")
        print(f"[fault] corrupted checkpoint file {victim}", flush=True)
        return True

    # -- reporting -------------------------------------------------------
    def summary(self) -> str:
        with self._lock:
            if not self.fired:
                return "no faults fired"
            return " ".join(f"{site}@{occ}" for site, occ in self.fired)
