"""Recovery policy + counters: turn tripwires into bounded self-healing.

:class:`RecoveryPolicy` is the one knob bundle shared by every recovery
consumer — the Trainer's NaN rollback loop, the prefetcher's transient
data-read retries, and the checkpoint fallback scan — so "how hard to
try before giving up" is configured in one place. All retries are
BOUNDED with exponential backoff, and the Trainer aborts after
``max_rollbacks`` CONSECUTIVE rollbacks: a persistent fault (bad data
shard, broken optimizer config) must still fail loudly rather than loop
forever re-tripping the same wire.

:class:`RecoveryCounters` is the audit trail: thread-safe counters
(rollbacks / ckpt_fallbacks / data_retries / lr_rewarms) that the
Trainer logs per epoch through ``Loggers`` (``recovery_*`` metrics) and
prints at the end of ``fit`` — a recovered run must say exactly what it
survived, or operators can't tell self-healing from silence.
"""

from __future__ import annotations

from dataclasses import dataclass

from deepvision_tpu.obs.metrics import Counter, Registry, default_registry

__all__ = [
    "NumericDivergence",
    "RecoveryCounters",
    "RecoveryError",
    "RecoveryPolicy",
]


class RecoveryError(RuntimeError):
    """Recovery budget exhausted — the run aborts loudly."""


class NumericDivergence(RuntimeError):
    """The checkify NaN/Inf tripwire fired at a known step; carries the
    position so the rollback can skip past the offending batch window."""

    def __init__(self, epoch: int, step_in_epoch: int,
                 cause: BaseException | None = None):
        self.epoch = int(epoch)
        self.step_in_epoch = int(step_in_epoch)
        super().__init__(
            f"NaN/Inf detected at epoch {epoch} step {step_in_epoch}"
            + (f": {cause}" if cause is not None else ""))


class RecoveryCounters:
    """Thread-safe recovery event counters (producer thread + step loop
    + checkpoint scan all increment).

    Each field is an :class:`obs.metrics.Counter` registered into
    ``registry`` (default: the process registry) under ``recovery_*``
    names — the SAME names ``train/loggers.recovery_metrics`` logs per
    epoch — so the merged obs snapshot and ``GET /metrics`` carry the
    recovery audit trail without a second bookkeeping path."""

    FIELDS = ("rollbacks", "ckpt_fallbacks", "data_retries", "lr_rewarms")

    def __init__(self, registry: Registry | None = None):
        reg = registry if registry is not None else default_registry()
        self._counts = {k: reg.register(f"recovery_{k}", Counter())
                        for k in self.FIELDS}

    def inc(self, name: str, n: int = 1) -> None:
        self._counts[name].inc(n)

    def get(self, name: str) -> int:
        return self._counts[name].value

    def snapshot(self) -> dict:
        """Plain-dict view; ``train/loggers.recovery_metrics`` flattens
        it into the per-epoch ``recovery_*`` metric surface."""
        return {k: c.value for k, c in self._counts.items()}

    def format(self) -> str:
        """Grep-stable one-liner (``make chaos-smoke`` asserts on it)."""
        return " ".join(f"{k}={v}" for k, v in self.snapshot().items())

    def __repr__(self) -> str:
        return f"RecoveryCounters({self.format()})"


@dataclass
class RecoveryPolicy:
    """Bounded-retry / rollback knobs.

    - ``max_data_retries``: transient read retries per batch pull before
      the error propagates (prefetcher).
    - ``backoff_s`` × ``backoff_mult`` (capped at ``max_backoff_s``):
      exponential backoff between retries/restarts.
    - ``max_rollbacks``: CONSECUTIVE NaN rollbacks before the Trainer
      aborts with :class:`RecoveryError` (a completed epoch resets the
      streak).
    - ``skip_batches``: how far past the offending step the rollback
      resumes (the "batch window" presumed poisoned).
    - ``lr_rewarm``: optional factor (<1) applied to the optimizer's
      ``lr_scale`` on each rollback — re-warming after a blow-up, the
      classic divergence response.
    """

    max_data_retries: int = 3
    backoff_s: float = 0.05
    backoff_mult: float = 2.0
    max_backoff_s: float = 2.0
    max_rollbacks: int = 3
    skip_batches: int = 1
    lr_rewarm: float | None = None

    def __post_init__(self):
        if self.max_data_retries < 0:
            raise ValueError("max_data_retries must be >= 0")
        if self.max_rollbacks < 1:
            raise ValueError("max_rollbacks must be >= 1")
        if self.skip_batches < 1:
            raise ValueError("skip_batches must be >= 1")
        if self.lr_rewarm is not None and not 0.0 < self.lr_rewarm <= 1.0:
            raise ValueError(f"lr_rewarm must be in (0, 1], "
                             f"got {self.lr_rewarm}")

    def backoff(self, attempt: int) -> float:
        """Delay before retry number ``attempt`` (0-based)."""
        return min(self.backoff_s * self.backoff_mult ** attempt,
                   self.max_backoff_s)
