"""Pipeline serving: device-resident DAGs of compiled stages.

Production traffic composes the zoo — detect -> crop -> per-person
pose, GAN upsample -> classify — but a naive composition makes each
hop a separate ``/v1/predict`` round-trip that drags tensors back to
the host, re-serializes them, and re-enters the queue. The pjit/TPU
systems line (PAPERS.md, arXiv 2204.06514) gets its throughput by
keeping composed computation device-resident between compiled
programs; this module does the same for the serving tier:

- :class:`ModelStage` — the compiled unit a ``ServedModel`` is made
  of: a pure ``(variables, batch) -> outputs`` forward plus explicit
  input/output avals (``in_avals``/``out_avals``, the ``export.py``
  seam), AOT-compiled per (stage, bucket, dtype).
- **Glue stages** (:func:`register_glue`): crop-from-boxes, top-K
  selection, resize-to-stage-bucket — themselves jitted device code
  compiled through the same cache, so the DAG never leaves the device
  until the final decode.
- :class:`PipelineSpec` — the declarative DAG (name -> nodes/edges),
  JSON-loadable (``serve.py --pipelines``).
- :class:`Pipeline` — the built DAG: validated **before any compile**
  (acyclic, aval-compatible edge by edge, bucket-ladder-divisible),
  then served by the engine exactly like a model — it quacks the
  ``ServedModel`` surface (``input_shape``/``buckets``/
  ``compile_for``/``postprocess``) so pipeline requests ride the
  existing bucket/compile-cache/admission path unchanged.

Execution contract:

- **device residency** — stage outputs feed stage inputs as device
  arrays; the only ``device_get`` is the engine's final decode
  (jaxlint JX127 guards this path).
- **fan-out** — one image -> K person crops -> a pose micro-batch:
  ``K`` is a compile-time constant, raggedness lives in the ``valid``
  mask (never in shapes), and the flattened ``B*K`` rows are chunked
  through each stage's own bucket ladder (:func:`chunk_plan`) — the
  same pad-to-bucket machinery the engine uses at the front door.
- **no hidden compiles** — because the engine pads every pipeline
  batch to an entry bucket first, each stage's chunk plan is a pure
  function of (entry bucket, fan-out), so ``warm()`` covers every
  (stage, bucket) executable end-to-end and the compile cache can be
  frozen after warmup.
- **per-stage spans** — when tracing is active the runner stamps one
  ``stage:<node>`` span per stage (synced at the stage boundary —
  observability mode deliberately trades the overlap), so one trace id
  flows router -> replica -> every stage in a single Perfetto timeline.
"""

from __future__ import annotations

import dataclasses
import json
import time
from pathlib import Path
from typing import Any, Callable

import numpy as np

__all__ = [
    "PipelineError", "PipelineNode", "PipelineOutput", "PipelineSpec",
    "Pipeline", "ModelStage", "register_glue", "chunk_plan",
    "load_pipeline_specs",
]


class PipelineError(ValueError):
    """A pipeline spec that cannot be served: cyclic, aval-mismatched
    edges, un-divisible bucket ladders, dangling references. Raised at
    build time, before any compile."""


# ------------------------------------------------------------ ModelStage


@dataclasses.dataclass
class ModelStage:
    """The compiled unit behind a ``ServedModel``: pure forward +
    variables + per-example input geometry, with explicit input/output
    avals so a DAG edge can be shape/dtype-checked before any compile
    (``export.py`` artifacts carry the same ``in_avals``/``out_avals``
    metadata — the seam is identical).

    ``ServedModel.compile_for`` delegates here (``as_stage()``), so the
    single-model engine path and the pipeline path share one AOT
    compile recipe; pipelines compile with ``donate=False`` because an
    inter-stage buffer may have several consumers (the detect input
    image is re-read by the crop glue)."""

    name: str
    forward: Callable
    variables: Any
    input_shape: tuple[int, ...]
    input_dtype: Any = np.float32
    precompiled: Callable | None = None
    pinned_buckets: tuple[int, ...] | None = None
    # tenancy seam: when set, runners read the live weights through
    # this zero-arg callable at CALL time instead of capturing
    # ``variables`` at compile time — the weights edition indirection
    # that lets eviction free HBM (the edition holds the only device
    # refs) while a hot-swap's old runners drain on their compile-time
    # edition. ``fingerprint`` is the weights content hash the compile
    # cache keys on (``"static"`` for stages outside tenancy).
    variables_ref: Callable | None = None
    fingerprint: str = "static"

    @property
    def dtype_str(self) -> str:
        return str(np.dtype(self.input_dtype))

    def in_avals(self, bucket: int):
        import jax

        return (jax.ShapeDtypeStruct(
            (bucket, *self.input_shape), self.input_dtype),)

    def out_avals(self, bucket: int):
        """Abstract output pytree at ``bucket`` via ``jax.eval_shape``
        — no FLOPs, no compile; what the DAG validator consumes."""
        import jax

        (x_spec,) = self.in_avals(bucket)
        return jax.eval_shape(self.forward, self.variables, x_spec)

    def compile(self, bucket: int, mesh, *, donate: bool = True):
        """AOT-compile the forward at ``(bucket, *input_shape)`` over
        ``mesh`` — batch sharded on the data axis, variables
        replicated — and return a runner ``x_device -> device
        outputs``. StableHLO-backed stages return their deserialized
        executable (already compiled, one shape)."""
        import warnings

        import jax

        from deepvision_tpu.core.mesh import (
            data_sharding,
            replicated_sharding,
        )

        if self.precompiled is not None:
            if self.pinned_buckets and bucket not in self.pinned_buckets:
                raise ValueError(
                    f"{self.name}: exported artifact is pinned to batch "
                    f"{self.pinned_buckets}, cannot serve bucket {bucket}")
            return self.precompiled
        x_spec = jax.ShapeDtypeStruct(
            (bucket, *self.input_shape), self.input_dtype)
        fn = jax.jit(
            self.forward,
            in_shardings=(replicated_sharding(mesh),
                          data_sharding(mesh, 1 + len(self.input_shape))),
            donate_argnums=(1,) if donate else (),
        )
        with warnings.catch_warnings():
            # CPU backends can't honor input donation; the donate is a
            # real HBM saving on TPU and a no-op warning elsewhere
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = fn.lower(self.variables, x_spec).compile()
        get = self.variables_ref
        if get is None:
            variables = self.variables

            def runner(x_device):
                return compiled(variables, x_device)
        else:
            def runner(x_device):
                # call-time read through the compile-time edition: the
                # local ref pins the device buffers for exactly this
                # call, so a concurrent evict/swap never tears a batch
                return compiled(get(), x_device)

        return runner


# ---------------------------------------------------------- glue stages


_GLUE: dict[str, Callable] = {}


def register_glue(name: str):
    """Register a glue-stage builder: ``build(params, in_avals) ->
    (fn, batch_factor)`` where ``fn`` is pure jit-able device code over
    the input arrays/pytrees and ``batch_factor`` is the fan-out of the
    output batch dim relative to the FIRST input's (crop-from-boxes
    returns K rows per image; most glue returns 1)."""

    def deco(build: Callable) -> Callable:
        _GLUE[name] = build
        return build

    return deco


def _require_keys(aval, keys: tuple[str, ...], glue: str) -> None:
    if not isinstance(aval, dict) or any(k not in aval for k in keys):
        have = sorted(aval) if isinstance(aval, dict) else type(aval)
        raise PipelineError(
            f"glue {glue!r} needs a detect-style dict input with keys "
            f"{keys}, got {have}")


@register_glue("top_k_boxes")
def _build_top_k_boxes(params: dict, in_avals: list):
    """Detect output dict -> the K best (optionally class-filtered)
    boxes per image: ``{"boxes": (B,K,4), "scores": (B,K),
    "valid": (B,K)}``. Invalid/padded detections score 0 and come out
    ``valid=False`` — raggedness stays in the mask."""
    import jax
    import jax.numpy as jnp

    k = int(params.get("k", 1))
    class_id = params.get("class_id")
    min_score = float(params.get("min_score", 0.0))
    (det,) = in_avals
    _require_keys(det, ("boxes", "scores", "valid"), "top_k_boxes")
    if k > det["scores"].shape[1]:
        raise PipelineError(
            f"top_k_boxes: k={k} exceeds the detector's max "
            f"{det['scores'].shape[1]} candidates")

    def fn(det):
        scores = det["scores"].astype(jnp.float32) \
            * det["valid"].astype(jnp.float32)
        if class_id is not None:
            scores = scores * (det["classes"] == class_id).astype(
                jnp.float32)
        top, idx = jax.lax.top_k(scores, k)
        boxes = jnp.take_along_axis(det["boxes"], idx[..., None], axis=1)
        return {"boxes": boxes, "scores": top, "valid": top > min_score}

    return fn, 1


@register_glue("crop_resize")
def _build_crop_resize(params: dict, in_avals: list):
    """(images, selected boxes) -> flattened per-box crops:
    ``{"crops": (B*K, S, S, C), "valid": (B*K,)}`` — the fan-out stage.
    K is the selector's compile-time box count; the flattened rows are
    what the downstream stage's bucket ladder chunks."""
    from deepvision_tpu.ops.crop_resize import crop_and_resize

    size = int(params["size"])
    images, sel = in_avals
    _require_keys(sel, ("boxes", "valid"), "crop_resize")
    k = int(sel["boxes"].shape[1])

    def fn(images, sel):
        crops = crop_and_resize(images, sel["boxes"], size)
        b = crops.shape[0]
        return {"crops": crops.reshape(b * k, size, size, crops.shape[-1]),
                "valid": sel["valid"].reshape(b * k)}

    return fn, k


@register_glue("resize")
def _build_resize(params: dict, in_avals: list):
    """Whole-image bilinear resize to a stage's input geometry."""
    from deepvision_tpu.ops.crop_resize import resize_bilinear

    size = int(params["size"])

    def fn(images):
        return resize_bilinear(images, size)

    return fn, 1


# ----------------------------------------------------------------- spec


@dataclasses.dataclass
class PipelineNode:
    """One DAG node: a model stage (``model=<served name>``) or a glue
    stage (``glue=<registered name>`` + ``params``). ``inputs`` are the
    edges: ``"input"`` (the request tensor), another node's name, or
    ``"node.key"`` to select one output of a dict-valued stage.
    ``buckets`` overrides this stage's chunking ladder."""

    name: str
    model: str | None = None
    glue: str | None = None
    inputs: tuple[str, ...] = ("input",)
    params: dict = dataclasses.field(default_factory=dict)
    buckets: tuple[int, ...] | None = None


@dataclasses.dataclass
class PipelineOutput:
    """One returned node. ``mask`` names a boolean plane (``node.key``)
    that gates fan-out rows at decode time — e.g. ``crop.valid`` keeps
    only the real person crops of each image's K slots."""

    node: str
    mask: str | None = None


@dataclasses.dataclass
class PipelineSpec:
    """Declarative pipeline: name -> nodes/edges (+ optional entry
    geometry and entry bucket ladder). ``input_shape`` may be omitted
    when exactly one MODEL node consumes ``"input"`` directly — its
    geometry is the pipeline's."""

    name: str
    nodes: list[PipelineNode]
    outputs: list[PipelineOutput]
    input_shape: tuple[int, ...] | None = None
    input_dtype: str = "float32"
    buckets: tuple[int, ...] | None = None

    @classmethod
    def from_json(cls, d: dict) -> "PipelineSpec":
        if not isinstance(d, dict) or "name" not in d or "nodes" not in d:
            raise PipelineError(
                f"pipeline spec needs 'name' and 'nodes', got {d!r}")
        nodes = [PipelineNode(
            name=n["name"], model=n.get("model"), glue=n.get("glue"),
            inputs=tuple(n.get("inputs", ("input",))),
            params=dict(n.get("params", {})),
            buckets=tuple(n["buckets"]) if n.get("buckets") else None,
        ) for n in d["nodes"]]
        outs = []
        for o in d.get("outputs", [nodes[-1].name if nodes else []]):
            if isinstance(o, str):
                outs.append(PipelineOutput(node=o))
            else:
                outs.append(PipelineOutput(node=o["node"],
                                           mask=o.get("mask")))
        inp = d.get("input", {})
        return cls(
            name=d["name"], nodes=nodes, outputs=outs,
            input_shape=(tuple(inp["shape"]) if inp.get("shape")
                         else None),
            input_dtype=inp.get("dtype", "float32"),
            buckets=tuple(d["buckets"]) if d.get("buckets") else None,
        )


def load_pipeline_specs(path: str | Path) -> list[PipelineSpec]:
    """Parse a ``--pipelines`` JSON file: one spec object, a list of
    them, or ``{"pipelines": [...]}``. Pure json — a fleet router can
    read the pipeline NAMES without importing jax."""
    body = json.loads(Path(path).read_text())
    if isinstance(body, dict) and "pipelines" in body:
        body = body["pipelines"]
    if isinstance(body, dict):
        body = [body]
    return [PipelineSpec.from_json(d) for d in body]


def chunk_plan(n: int, ladder: tuple[int, ...]) -> list[tuple[int, int, int]]:
    """Chunk ``n`` rows through a bucket ladder: ``[(start, rows,
    bucket), ...]``. Full max-ladder chunks first, then one padded
    chunk at the smallest bucket that fits the remainder — the same
    policy the engine's front door applies to a request backlog, so
    ragged fan-out traffic reuses the exact executables warmup built."""
    if n <= 0 or not ladder:
        raise PipelineError(f"chunk_plan: n={n} ladder={ladder}")
    plan, i = [], 0
    while i < n:
        rem = n - i
        bucket = max(ladder)
        for b in ladder:
            if b >= rem:
                bucket = b
                break
        rows = min(rem, bucket)
        plan.append((i, rows, bucket))
        i += rows
    return plan


# ------------------------------------------------------------- Pipeline


_INPUT = "input"


class Pipeline:
    """A built, validated DAG of compiled stages, served by the engine
    through the ``ServedModel`` surface. Construction validates the
    spec end to end (structure, acyclicity, per-edge aval
    compatibility via ``eval_shape`` — zero compiles); ``bind()``
    (called by the engine at registration) attaches the shared compile
    cache + mesh and checks every stage ladder divides the mesh's data
    axis; ``compile_for(bucket, mesh)`` builds the device-resident
    runner, compiling every (stage, chunk-bucket) executable through
    the shared cache so ``engine.warm()`` covers the whole DAG."""

    is_pipeline = True
    task = "pipeline"
    scale = "unit"
    variables = None
    precompiled = None

    def __init__(self, spec: PipelineSpec, models: dict,
                 *, default_buckets: tuple[int, ...] = (1, 4, 16, 64)):
        self.spec = spec
        self.name = spec.name
        self._models = dict(models)
        self._default_buckets = tuple(default_buckets)
        self._cache = None  # bound by the engine (or bind())
        self._mesh = None
        self.requests_served = 0
        self._stage_stamps: list[tuple[str, float, float]] = []
        self.last_chunk_plans: dict[str, list] = {}
        # test/chaos instrumentation: called (on the dispatcher thread,
        # host-side) after each stage completes; never on the fast path
        self.stage_hook: Callable[[str], None] | None = None
        self._validate_structure()
        self._order = self._toposort()
        self._stages = self._build_stages()
        # canonical aval walk: per-edge shape/dtype validation happens
        # HERE, before any compile (entry bucket scales linearly, so
        # one bucket proves the family)
        self._walk_avals(self._canonical_bucket())

    # -- ServedModel-quacking surface ------------------------------------
    @property
    def buckets(self) -> tuple[int, ...]:
        return tuple(self.spec.buckets or self._default_buckets)

    @property
    def input_shape(self) -> tuple[int, ...]:
        shape, _ = self._entry_geometry()
        return shape

    @property
    def input_dtype(self):
        _, dtype = self._entry_geometry()
        return dtype

    @property
    def dtype_str(self) -> str:
        return str(np.dtype(self.input_dtype))

    # -- validation ------------------------------------------------------
    def _node_map(self) -> dict[str, PipelineNode]:
        return {n.name: n for n in self.spec.nodes}

    def _validate_structure(self) -> None:
        spec = self.spec
        if not spec.nodes:
            raise PipelineError(f"pipeline {spec.name!r} has no nodes")
        names = [n.name for n in spec.nodes]
        if len(set(names)) != len(names):
            raise PipelineError(
                f"pipeline {spec.name!r}: duplicate node names {names}")
        if _INPUT in names:
            raise PipelineError(
                f"pipeline {spec.name!r}: {_INPUT!r} is the reserved "
                "entry edge, not a node name")
        known = set(names)
        for n in spec.nodes:
            if bool(n.model) == bool(n.glue):
                raise PipelineError(
                    f"node {n.name!r}: exactly one of model= / glue= "
                    "must be set")
            if n.model and n.model not in self._models:
                raise PipelineError(
                    f"node {n.name!r}: unknown model {n.model!r}; "
                    f"serving {sorted(self._models)}")
            if n.glue and n.glue not in _GLUE:
                raise PipelineError(
                    f"node {n.name!r}: unknown glue {n.glue!r}; "
                    f"registered: {sorted(_GLUE)}")
            if n.model and len(n.inputs) != 1:
                raise PipelineError(
                    f"model node {n.name!r} takes exactly one input "
                    f"edge, got {n.inputs}")
            for ref in n.inputs:
                src = ref.split(".", 1)[0]
                if src != _INPUT and src not in known:
                    raise PipelineError(
                        f"node {n.name!r}: input {ref!r} references "
                        f"unknown node {src!r}")
        if not spec.outputs:
            raise PipelineError(f"pipeline {spec.name!r} has no outputs")
        for o in spec.outputs:
            if o.node not in known:
                raise PipelineError(
                    f"output references unknown node {o.node!r}")
            if o.mask and o.mask.split(".", 1)[0] not in known:
                raise PipelineError(
                    f"output mask {o.mask!r} references an unknown node")

    def _toposort(self) -> list[PipelineNode]:
        nodes = self._node_map()
        deps = {n.name: {ref.split(".", 1)[0] for ref in n.inputs
                         if ref.split(".", 1)[0] != _INPUT}
                for n in self.spec.nodes}
        order, ready = [], sorted(n for n, d in deps.items() if not d)
        deps = {n: set(d) for n, d in deps.items() if d}
        while ready:
            name = ready.pop(0)
            order.append(nodes[name])
            for other in sorted(deps):
                deps[other].discard(name)
                if not deps[other]:
                    del deps[other]
                    ready.append(other)
        if deps:
            raise PipelineError(
                f"pipeline {self.spec.name!r} has a cycle through "
                f"{sorted(deps)}")
        return order

    def _entry_geometry(self) -> tuple[tuple[int, ...], Any]:
        if self.spec.input_shape is not None:
            return tuple(self.spec.input_shape), np.dtype(
                self.spec.input_dtype)
        consumers = [n for n in self.spec.nodes
                     if _INPUT in n.inputs and n.model]
        if len(consumers) == 1:
            served = self._models[consumers[0].model]
            return tuple(served.input_shape), np.dtype(served.input_dtype)
        raise PipelineError(
            f"pipeline {self.spec.name!r}: give an explicit input "
            "shape — the entry geometry is only inferable when exactly "
            "one model node consumes 'input' directly")

    def _canonical_bucket(self) -> int:
        return min(self.buckets)

    def _build_stages(self) -> dict[str, dict]:
        """node name -> {"kind", "served"/"build", "ladder"} — resolved
        once. ``as_stage()`` is taken lazily at walk/compile time, NOT
        here: the engine replicates each served model's variables onto
        the mesh after construction, and a stage snapshot taken now
        would compile against the pre-placement weights."""
        stages = {}
        for node in self._order:
            if node.model:
                served = self._models[node.model]
                ladder = tuple(node.buckets or served.buckets
                               or self._default_buckets)
                stages[node.name] = {"kind": "model", "served": served,
                                     "ladder": ladder}
            else:
                stages[node.name] = {"kind": "glue",
                                     "build": _GLUE[node.glue]}
        return stages

    def stage_models(self) -> dict[str, Any]:
        """The served models this DAG's model nodes reference (shared
        objects with the engine's plain path) — what the engine
        replicates onto the mesh."""
        return {n.model: self._models[n.model]
                for n in self._order if n.model}

    def _select_aval(self, env: dict, ref: str, node: str):
        src, _, key = ref.partition(".")
        val = env[src]
        if key:
            if not isinstance(val, dict) or key not in val:
                raise PipelineError(
                    f"node {node!r}: input {ref!r} selects key "
                    f"{key!r} but {src!r} produces "
                    f"{sorted(val) if isinstance(val, dict) else type(val)}")
            return val[key]
        return val

    def _walk_avals(self, bucket: int) -> dict:
        """Abstract-evaluate the whole DAG at an entry bucket: per-edge
        shape/dtype checks, per-node output avals + fan-out factors.
        Zero compiles (``jax.eval_shape`` only) — this is the validator
        the ``out_avals`` seam exists for."""
        import jax

        shape, dtype = self._entry_geometry()
        env = {_INPUT: jax.ShapeDtypeStruct((bucket, *shape), dtype)}
        factors = {_INPUT: 1}
        glue_fns: dict[str, Callable] = {}
        for node in self._order:
            ins = [self._select_aval(env, ref, node.name)
                   for ref in node.inputs]
            info = self._stages[node.name]
            if info["kind"] == "model":
                stage = info["served"].as_stage()
                (aval,) = ins
                if not hasattr(aval, "shape"):
                    raise PipelineError(
                        f"model node {node.name!r} needs an array "
                        f"input, got {type(aval)} from "
                        f"{node.inputs[0]!r}")
                if tuple(aval.shape[1:]) != tuple(stage.input_shape) \
                        or np.dtype(aval.dtype) != np.dtype(
                            stage.input_dtype):
                    raise PipelineError(
                        f"aval mismatch on edge {node.inputs[0]!r} -> "
                        f"{node.name!r}: stage expects per-example "
                        f"{tuple(stage.input_shape)} "
                        f"{np.dtype(stage.input_dtype)}, got "
                        f"{tuple(aval.shape[1:])} {np.dtype(aval.dtype)}")
                env[node.name] = stage.out_avals(int(aval.shape[0]))
                factors[node.name] = factors[
                    node.inputs[0].split(".", 1)[0]]
            else:
                fn, batch_factor = info["build"](node.params, ins)
                glue_fns[node.name] = fn
                try:
                    env[node.name] = jax.eval_shape(fn, *ins)
                except (TypeError, ValueError) as e:
                    raise PipelineError(
                        f"glue node {node.name!r} rejects its input "
                        f"avals: {e}") from e
                factors[node.name] = factors[
                    node.inputs[0].split(".", 1)[0]] * batch_factor
        for o in self.spec.outputs:
            if o.mask:
                mask_aval = self._select_aval(env, o.mask, o.node)
                src = o.mask.split(".", 1)[0]
                if factors[src] != factors[o.node]:
                    raise PipelineError(
                        f"output {o.node!r}: mask {o.mask!r} has "
                        f"fan-out {factors[src]}, output has "
                        f"{factors[o.node]}")
                if not hasattr(mask_aval, "shape"):
                    raise PipelineError(
                        f"output mask {o.mask!r} must be an array")
        self._factors = factors
        return {"env": env, "factors": factors, "glue_fns": glue_fns}

    # -- binding / compilation -------------------------------------------
    def bind(self, cache, mesh,
             default_buckets: tuple[int, ...] | None = None) -> None:
        """Attach the engine's shared compile cache + mesh (called at
        registration) and check every stage ladder divides the mesh
        data axis — batches shard over it at every stage, not just the
        front door."""
        from deepvision_tpu.core.mesh import axis_size

        if default_buckets:
            self._default_buckets = tuple(default_buckets)
            self._stages = self._build_stages()
        self._cache = cache
        self._mesh = mesh
        n_data = axis_size(mesh)
        for node in self._order:
            info = self._stages[node.name]
            if info["kind"] != "model":
                continue
            for b in info["ladder"]:
                if b % n_data:
                    raise PipelineError(
                        f"pipeline {self.name!r} stage {node.name!r}: "
                        f"bucket {b} is not divisible by the mesh data "
                        f"axis ({n_data})")

    def _ensure_bound(self, mesh) -> None:
        if self._cache is None:
            from deepvision_tpu.serve.compile_cache import CompileCache

            self.bind(CompileCache(max_entries=256), mesh)

    def compile_for(self, bucket: int, mesh):
        """Build the device-resident runner for one entry bucket:
        every (stage, chunk-bucket, dtype) executable and every glue
        program compiles through the shared cache NOW — this is what
        ``engine.warm()`` calls, so a warmed pipeline never pays a
        request-time trace."""
        import jax

        self._ensure_bound(mesh)
        walk = self._walk_avals(bucket)
        env_avals, glue_fns = walk["env"], walk["glue_fns"]
        cache = self._cache
        executors: list[tuple[PipelineNode, Callable]] = []
        for node in self._order:
            info = self._stages[node.name]
            in_avals = [self._select_aval(env_avals, ref, node.name)
                        for ref in node.inputs]
            if info["kind"] == "model":
                executors.append((node, self._model_executor(
                    node, info, int(in_avals[0].shape[0]), mesh)))
            else:
                rows = int(jax.tree_util.tree_leaves(
                    in_avals[0])[0].shape[0])
                key = (f"{self.name}/{node.name}#{node.glue}", rows,
                       self.dtype_str)
                fn = glue_fns[node.name]
                runner = cache.get_or_build(
                    key, lambda fn=fn, avals=in_avals:
                    jax.jit(fn).lower(*avals).compile())
                executors.append((node, runner))
        return self._make_runner(executors)

    def _model_executor(self, node: PipelineNode, info: dict,
                        rows: int, mesh):
        """Chunk ``rows`` inter-stage rows through this stage's own
        ladder; every chunk executable (and the pad program for the
        ragged tail) compiles through the shared cache. Stage
        executables are keyed ``(pipeline:model, bucket, dtype,
        weights fingerprint)`` — distinct from the engine's front-door
        key because pipeline
        stages compile WITHOUT input donation (inter-stage buffers can
        have several consumers)."""
        import jax
        import jax.numpy as jnp

        stage = info["served"].as_stage()
        plan = chunk_plan(rows, info["ladder"])
        cache = self._cache
        runners = {}
        for _start, k, b in plan:
            key = (f"pipeline:{stage.name}", b, stage.dtype_str,
                   stage.fingerprint)
            runners[b] = cache.get_or_build(
                key, lambda b=b: stage.compile(b, mesh, donate=False))
            if k < b:
                tail = (b - k, *stage.input_shape)
                pad_key = ("pipeline:pad", (k, b) + tuple(
                    stage.input_shape), stage.dtype_str)
                runners[(k, b)] = cache.get_or_build(
                    pad_key, lambda k=k, b=b:
                    jax.jit(lambda a: jnp.concatenate(
                        [a, jnp.zeros((b - k,) + a.shape[1:],
                                      a.dtype)], axis=0)).lower(
                        jax.ShapeDtypeStruct(
                            (k, *stage.input_shape),
                            stage.input_dtype)).compile())
        self.last_chunk_plans[node.name] = plan

        def run_model_stage(x):
            outs = []
            for start, k, b in plan:
                xa = x[start:start + k] if (start or k < rows) else x
                if k < b:
                    xa = runners[(k, b)](xa)
                o = runners[b](xa)
                if k < b:
                    o = jax.tree_util.tree_map(lambda a: a[:k], o)
                outs.append(o)
            if len(outs) == 1:
                return outs[0]
            return jax.tree_util.tree_map(
                lambda *xs: jnp.concatenate(xs, axis=0), *outs)

        return run_model_stage

    def _make_runner(self, executors):
        """The device-resident DAG executor: stage outputs feed stage
        inputs as device arrays — the only host fetch is the engine's
        final decode. When tracing is active, each stage boundary syncs
        once so the ``stage:<node>`` spans are honest (observability
        mode deliberately trades the overlap; JX112/JX117 contract)."""
        import jax

        from deepvision_tpu.obs.trace import get_tracer

        spec = self.spec
        factors = self._factors
        select = self._select_value

        def run_pipeline(xd):
            tracer = get_tracer()
            env = {_INPUT: xd}
            stamps: list[tuple[str, float, float]] = []
            for node, execute in executors:
                ins = [select(env, ref) for ref in node.inputs]
                t0 = time.perf_counter()
                out = execute(*ins) if len(ins) > 1 else execute(ins[0])
                if tracer.active:
                    # traced mode only: sync at the stage boundary so
                    # the per-stage span measures compute, not enqueue
                    out = jax.block_until_ready(out)  # jaxlint: disable=JX127
                    stamps.append((node.name, t0, time.perf_counter()))
                env[node.name] = out
                if self.stage_hook is not None:
                    self.stage_hook(node.name)
            self._stage_stamps = stamps
            result = {}
            for o in spec.outputs:
                result[o.node] = self._fold_fanout(
                    env[o.node], factors[o.node])
                if o.mask:
                    mask = select(env, o.mask)
                    result[f"{o.node}__mask"] = self._fold_fanout(
                        mask, factors[o.mask.split('.', 1)[0]])
            return result

        return run_pipeline

    @staticmethod
    def _select_value(env: dict, ref: str):
        src, _, key = ref.partition(".")
        return env[src][key] if key else env[src]

    def _fold_fanout(self, val, factor: int):
        """(B*F, ...) fan-out leaves -> (B, F, ...) so the decode can
        index per original request."""
        import jax

        if factor == 1:
            return val
        return jax.tree_util.tree_map(
            lambda a: a.reshape(a.shape[0] // factor, factor,
                                *a.shape[1:]), val)

    def take_stage_stamps(self) -> list[tuple[str, float, float]]:
        """Per-stage (node, t0, t1) stamps of the last traced run —
        consumed (and cleared) by the engine to record ``stage:<node>``
        spans against the batch's trace ids."""
        stamps, self._stage_stamps = self._stage_stamps, []
        return stamps

    def record_served(self, rows: int) -> None:
        self.requests_served += rows

    # -- decode ----------------------------------------------------------
    def postprocess(self, host: dict, i: int) -> dict:
        """Row ``i`` of the fetched DAG outputs -> JSON-able dict, one
        entry per declared output node. Model-stage outputs decode with
        that stage's own task postprocess; fan-out outputs decode as a
        list over the K slots, masked rows dropped."""
        result = {}
        nodes = self._node_map()
        for o in self.spec.outputs:
            sub = host[o.node]
            node = nodes[o.node]
            served = (self._stages[o.node]["served"]
                      if node.model else None)
            factor = self._factors[o.node]
            if factor == 1:
                result[o.node] = (served.postprocess(sub, i)
                                  if served else _row_jsonable(sub, i))
                continue
            mask = host.get(f"{o.node}__mask")
            import jax

            sub_i = jax.tree_util.tree_map(lambda a: a[i], sub)
            rows = []
            for j in range(factor):
                if mask is not None and not bool(np.asarray(mask[i][j])):
                    continue
                rows.append(served.postprocess(sub_i, j)
                            if served else _row_jsonable(sub_i, j))
            result[o.node] = rows
        return result


def _row_jsonable(val, i: int):
    if isinstance(val, dict):
        return {k: _row_jsonable(v, i) for k, v in val.items()}
    return np.asarray(val[i]).tolist()
