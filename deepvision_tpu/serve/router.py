"""SLO-aware fleet router: health-gated load balancing over N replicas.

The front tier the ROADMAP's millions-of-users story needs (item 4, and
the TPU-pod playbook of PAPERS.md 1909.09756/2204.06514): capacity AND
availability come from a fleet of replicas, not one bigger worker. A
:class:`FleetRouter` supervises N replicas (``serve/replica.py``:
in-process engines for tests, ``serve.py`` child processes in
production) and turns them into one serving surface with the properties
a single engine cannot have:

- **health-gated balancing** — requests go to the least-loaded READY
  replica; a replica whose ``/healthz`` degrades (the PR 4 supervisor
  recovery path) is DRAINED the moment the probe loop sees the 503 —
  no fresh traffic routes into a restart window.
- **failover with exactly-once results** — an attempt that dies with
  the replica is retried on a different replica (bounded), and a slow
  attempt is *hedged*: a duplicate launches after ``hedge_after_s`` and
  the first response wins. Every request resolves its future exactly
  once, no matter how many attempts raced for it.
- **circuit breaker + error budget** — per-model rolling failure
  windows: when a model's replicas keep failing, the breaker opens and
  the router sheds fast (429 + ``Retry-After``) instead of queueing
  doomed work; a half-open probe closes it once the model recovers.
- **SLO-aware admission** — per-model p95 deadline budgets feed the
  admission EWMA (``admission.AdmissionController``): a request that
  would wait past its model's budget is shed at the door with an
  honest retry hint, and the budget doubles as the default deadline.
- **supervision + metric-driven autoscaling** — dead replicas are
  respawned with capped stop-responsive backoff; an :class:`Autoscaler`
  reads the obs-registry signals the probe loop publishes (fleet
  queue-wait p95, shed rate, dispatcher crashes) and adds/drains
  replicas inside ``[min_replicas, max_replicas]`` with hysteresis
  (sustain counts + cooldown) so it never flaps.

Chaos sites ``replica_kill`` / ``replica_slow``
(``resilience/faults.py``) consult per routed attempt with monotonic
occurrence counters, so router chaos tests replay bit-identically —
and ``bench.py serve --sweep`` SIGKILLs a real child process at peak
load to prove the error budget instead of claiming it.
"""

from __future__ import annotations

import sys
import threading
import time
import zlib
from collections import deque
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    InvalidStateError,
    ThreadPoolExecutor,
    wait as futures_wait,
)
from dataclasses import dataclass
from typing import Callable

from deepvision_tpu.obs.distributed import new_trace_id, render_federated
from deepvision_tpu.obs.trace import span
from deepvision_tpu.serve.admission import AdmissionController, ShedError
from deepvision_tpu.serve.replica import ReplicaDeadError
from deepvision_tpu.serve.telemetry import RouterTelemetry

__all__ = [
    "AutoscaleConfig",
    "Autoscaler",
    "CircuitBreaker",
    "CircuitConfig",
    "FleetRouter",
    "RouterShedError",
]

# replica slot states
STARTING = "starting"
READY = "ready"
DRAINING = "draining"   # health-gated: probe saw a non-ok status
RETIRING = "retiring"   # autoscale-down: drain then stop
DEAD = "dead"
STOPPED = "stopped"


class RouterShedError(ShedError):
    """Router-originated shed (circuit open / no READY replica). Same
    ``retry_after_s`` contract as the admission :class:`ShedError`, so
    both CLI surfaces emit the identical 429 + ``Retry-After`` hint."""


# ------------------------------------------------------ circuit breaker


@dataclass
class CircuitConfig:
    """Per-model rolling error budget. The breaker trips OPEN when, over
    the last ``window`` attempts (and at least ``min_volume`` of them),
    the failure fraction exceeds ``failure_frac``; it stays open for
    ``open_s``, then HALF_OPEN admits one probe request — success
    closes, failure re-opens."""

    window: int = 32
    min_volume: int = 8
    failure_frac: float = 0.5
    open_s: float = 2.0


class CircuitBreaker:
    """closed -> open -> half-open -> closed, per model."""

    def __init__(self, cfg: CircuitConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or CircuitConfig()
        self._clock = clock
        self._lock = threading.Lock()
        self._outcomes: list[bool] = []  # rolling window, True = failure
        self.state = "closed"
        self._open_until = 0.0
        self._probe_inflight = False
        self._probe_started = 0.0

    def allow(self) -> bool:
        """May a request proceed right now? HALF_OPEN admits one probe
        at a time; a probe whose outcome never lands (shed before any
        replica attempt) expires after ``open_s`` so the slot cannot
        leak the breaker permanently open."""
        with self._lock:
            if self.state == "closed":
                return True
            now = self._clock()
            if self.state == "open":
                if now < self._open_until:
                    return False
                self.state = "half_open"
                self._probe_inflight = False
            # half_open: one probe in flight at a time (timed-out probes
            # forfeit the slot)
            if self._probe_inflight \
                    and now - self._probe_started < self.cfg.open_s:
                return False
            self._probe_inflight = True
            self._probe_started = now
            return True

    def retry_after_s(self) -> float:
        with self._lock:
            return round(max(0.05, self._open_until - self._clock()), 3)

    def record_success(self) -> None:
        with self._lock:
            if self.state == "half_open":
                self.state = "closed"
                self._outcomes.clear()
                self._probe_inflight = False
                return
            self._push(False)

    def record_failure(self) -> None:
        with self._lock:
            if self.state == "half_open":
                self._trip()
                return
            self._push(True)
            n = len(self._outcomes)
            if n >= self.cfg.min_volume and (
                    sum(self._outcomes) / n) > self.cfg.failure_frac:
                self._trip()

    def _push(self, failed: bool) -> None:
        self._outcomes.append(failed)
        if len(self._outcomes) > self.cfg.window:
            del self._outcomes[0]

    def _trip(self) -> None:
        self.state = "open"
        self._open_until = self._clock() + self.cfg.open_s
        self._outcomes.clear()
        self._probe_inflight = False

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "window_failures": sum(self._outcomes),
                    "window_size": len(self._outcomes)}


# ----------------------------------------------------------- autoscaler


@dataclass
class AutoscaleConfig:
    """Hysteresis knobs for the metric-driven autoscaler. Pressure
    (queue-wait p95 over ``up_queue_p95_ms``, shed rate over
    ``up_shed_rate_per_s``, or fresh dispatcher crashes) must SUSTAIN
    for ``sustain_up`` consecutive ticks to add a replica; calm must
    sustain for ``sustain_down`` ticks to drain one; ``cooldown_s``
    blocks back-to-back actions. ``down_queue_p95_ms`` sits well below
    ``up_queue_p95_ms`` so the two thresholds can never chatter."""

    min_replicas: int = 1
    max_replicas: int = 4
    interval_s: float = 1.0
    up_queue_p95_ms: float = 200.0
    up_shed_rate_per_s: float = 0.5
    down_queue_p95_ms: float = 20.0
    sustain_up: int = 2
    sustain_down: int = 5
    cooldown_s: float = 5.0


class Autoscaler:
    """Pure decision core (injectable clock): ``tick()`` maps one
    signal sample to a new replica target. Kept free of fleet plumbing
    so hysteresis is unit-testable without replicas or wall time."""

    def __init__(self, cfg: AutoscaleConfig | None = None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or AutoscaleConfig()
        self._clock = clock
        self._pressure_ticks = 0
        self._calm_ticks = 0
        self._last_action_t = -float("inf")
        self._last_crashes = 0.0

    def tick(self, *, queue_p95_ms: float, shed_rate_per_s: float,
             dispatcher_crashes: float, target: int,
             now: float | None = None) -> int:
        cfg = self.cfg
        now = self._clock() if now is None else now
        crashed = dispatcher_crashes > self._last_crashes
        self._last_crashes = max(self._last_crashes, dispatcher_crashes)
        pressure = (queue_p95_ms > cfg.up_queue_p95_ms
                    or shed_rate_per_s > cfg.up_shed_rate_per_s
                    or crashed)
        calm = (not pressure and shed_rate_per_s == 0.0
                and queue_p95_ms < cfg.down_queue_p95_ms)
        self._pressure_ticks = self._pressure_ticks + 1 if pressure else 0
        self._calm_ticks = self._calm_ticks + 1 if calm else 0
        in_cooldown = now - self._last_action_t < cfg.cooldown_s
        if (pressure and self._pressure_ticks >= cfg.sustain_up
                and target < cfg.max_replicas and not in_cooldown):
            self._last_action_t = now
            self._pressure_ticks = 0
            return target + 1
        if (calm and self._calm_ticks >= cfg.sustain_down
                and target > cfg.min_replicas and not in_cooldown):
            self._last_action_t = now
            self._calm_ticks = 0
            return target - 1
        return target


# ---------------------------------------------------------- fleet router


class _Slot:
    """One supervised replica position in the fleet."""

    __slots__ = ("sid", "replica", "state", "inflight", "generation")

    def __init__(self, sid: str, replica, state: str, generation: int):
        self.sid = sid
        self.replica = replica
        self.state = state
        self.inflight = 0
        self.generation = generation


class _Request:
    """One routed request: resolve-once future + routing context."""

    __slots__ = ("model", "key", "x", "future", "t_submit", "deadline",
                 "trace", "session", "seq", "_resolved", "_lock")

    def __init__(self, model: str | None, x, deadline: float,
                 key: str | None = None, trace: str | None = None,
                 session: str | None = None, seq: int | None = None):
        self.model = model
        self.key = key if key is not None else (model or "_default")
        self.x = x
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        # distributed trace id: minted here (the fleet's front door)
        # unless an upstream surface already assigned one; every
        # attempt span and the replica-side spans carry it
        self.trace = trace if trace is not None else new_trace_id()
        # stateful stream identity (serve/sessions.py): frames of one
        # session hash-pin to a replica and dispatch strictly in order
        self.session = session
        self.seq = seq
        self._resolved = False
        self._lock = threading.Lock()

    def resolve(self, result=None, error: BaseException | None = None
                ) -> bool:
        """Exactly-once: True for the attempt that won, False for every
        late hedge/duplicate — the 'no duplicate responses' guarantee."""
        with self._lock:
            if self._resolved:
                return False
            self._resolved = True
        try:
            if error is not None:
                self.future.set_exception(error)
            else:
                self.future.set_result(result)
        except InvalidStateError:  # client cancelled; nothing to deliver
            pass
        return True


class _SessionRoute:
    """Router-side state for one sticky stream: the pinned replica slot
    id, the strictly-FIFO frame queue, and the bounded client-side
    replay window (the frames between the last snapshot and a replica
    death that can be re-sent instead of declaring a reset)."""

    __slots__ = ("sid", "pin", "queue", "active", "window", "last_used")

    def __init__(self, sid: str, window: int):
        self.sid = sid
        self.pin: str | None = None      # slot id the stream sticks to
        self.queue: list = []            # [(req, breaker, key)] FIFO
        self.active = False              # a drain task is running
        self.window: deque = deque(maxlen=window)  # [(seq, x)]
        self.last_used = time.monotonic()


class FleetRouter:
    """Route requests across a supervised fleet of replicas.

    ``replica_factory(sid)`` builds (but does not start) a fresh replica
    for slot id ``sid`` — the router starts it, probes it, and respawns
    through the same factory after a death. ``slo`` maps model name ->
    p95 deadline budget in seconds: it becomes both the model's default
    request deadline and its admission budget (see
    ``AdmissionController.slo_budget_s``). ``tenant_quota`` /
    ``slo_class`` thread the multi-tenant isolation story into the
    router's own admission gate — a noisy tenant sheds at the FLEET
    front door before it can crowd any replica's queue.
    """

    def __init__(
        self,
        replica_factory: Callable[[str], object],
        *,
        replicas: int = 2,
        models: list[str] | None = None,
        slo: dict[str, float] | None = None,
        default_deadline_s: float = 30.0,
        max_queue: int = 256,
        per_model_limit: int | None = None,
        probe_interval_s: float = 0.25,
        max_retries: int = 2,
        hedge_after_s: float | None = None,
        restart_backoff_s: float = 0.2,
        restart_backoff_max_s: float = 10.0,
        circuit: CircuitConfig | None = None,
        autoscale: AutoscaleConfig | None = None,
        max_workers: int = 32,
        fault_injector=None,
        telemetry: RouterTelemetry | None = None,
        start: bool = True,
        session_replay_window: int = 32,
        tenant_quota: dict[str, int] | None = None,
        slo_class: dict[str, str] | None = None,
    ):
        if replicas < 1:
            raise ValueError(f"need >= 1 replica, got {replicas}")
        self._factory = replica_factory
        self._models = list(models or [])
        self._slo = dict(slo or {})
        self._default_deadline_s = default_deadline_s
        self._probe_interval_s = probe_interval_s
        self._max_retries = max_retries
        self._hedge_after_s = hedge_after_s
        self._restart_backoff_s = restart_backoff_s
        self._restart_backoff_max_s = restart_backoff_max_s
        self._circuit_cfg = circuit or CircuitConfig()
        self._autoscale_cfg = autoscale
        self.telemetry = telemetry if telemetry is not None \
            else RouterTelemetry()
        self._admission = AdmissionController(
            max_queue=max_queue, per_model_limit=per_model_limit,
            slo_budget_s=self._slo or None,
            tenant_quota=tenant_quota, slo_class=slo_class)
        self._injector = fault_injector
        self._lock = threading.Lock()
        self._session_replay_window = max(0, int(session_replay_window))
        self._sessions: dict[str, _SessionRoute] = {}
        self._slots: list[_Slot] = []
        self._gen = 0
        self._breakers: dict[str, CircuitBreaker] = {}
        self._stop = threading.Event()
        self._respawners: list[threading.Thread] = []
        self._backoff = restart_backoff_s
        self._target = replicas
        if autoscale is not None:
            self._target = max(autoscale.min_replicas,
                               min(replicas, autoscale.max_replicas))
            self._autoscaler = Autoscaler(autoscale)
        else:
            self._autoscaler = None
        self._last_shed_totals = 0.0
        self._last_signal_t = time.monotonic()
        self._autoscale_due = time.monotonic()
        self._flight_note_due = time.monotonic()
        self._respawn_not_before = 0.0
        # TWO pools: coordinators (one per in-flight request) and
        # replica attempts (<= 2 per RUNNING coordinator, so 2x workers
        # can never starve) — one shared pool would deadlock the moment
        # every worker held a coordinator waiting on a queued attempt
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="router-dispatch")
        self._attempt_pool = ThreadPoolExecutor(
            max_workers=2 * max_workers,
            thread_name_prefix="router-attempt")
        self.telemetry.replicas_target.set(self._target)
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        """Start the initial fleet (replicas boot in parallel) and the
        probe/supervisor thread. Raises if NO replica comes up."""
        with self._lock:
            target = self._target
        threads = [self._spawn_slot_async() for _ in range(target)]
        for t in threads:
            t.join()
        if not self._ready_slots():
            self.close()
            raise RuntimeError("no replica became ready at startup")
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True)
        self._probe_thread.start()

    def close(self, timeout: float = 30.0) -> None:
        """Graceful: stop probing, let in-flight dispatches finish
        (replicas stay up until the pool drains), then stop replicas.
        Idempotent."""
        if self._stop.is_set():
            return
        self._stop.set()
        t = getattr(self, "_probe_thread", None)
        if t is not None:
            t.join(timeout)
        self._pool.shutdown(wait=True)
        self._attempt_pool.shutdown(wait=True)
        with self._lock:
            respawners = list(self._respawners)
        for th in respawners:
            th.join(timeout)
        with self._lock:
            slots = list(self._slots)
        stoppers = []
        for s in slots:
            th = threading.Thread(target=self._stop_replica, args=(s,),
                                  name=f"router-stop-{s.sid}")
            th.start()
            stoppers.append(th)
        for th in stoppers:
            th.join(timeout)

    @staticmethod
    def _stop_replica(slot: _Slot) -> None:
        try:
            slot.replica.stop()
        except Exception:
            pass
        slot.state = STOPPED

    @staticmethod
    def _kill_replica(slot: _Slot) -> None:
        try:
            slot.replica.kill()
        except Exception:
            pass

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- client surface --------------------------------------------------
    @property
    def buckets(self) -> tuple[int, ...]:
        """Pipelining-window hint for the stdin-JSONL surface (the
        fleet analog of the engine's bucket ladder)."""
        return (64,)

    def submit(self, x, model: str | None = None, *,
               timeout_s: float | None = None,
               trace: str | None = None,
               session: str | None = None,
               seq: int | None = None) -> Future:
        """Route one example; returns a Future resolving to the task's
        result dict. Sheds raise immediately (circuit open / admission),
        the same :class:`ShedError` contract as the engine. ``trace``
        carries an upstream trace id; absent, the router mints one —
        either way every replica attempt propagates it over the
        ``X-DVTPU-Trace`` hop.

        ``session``/``seq`` mark a stateful stream frame: frames of one
        session hash-pin to a replica, dispatch strictly in submission
        order (per-stream FIFO, streams still parallel), and survive
        the pin's death via re-pin + snapshot restore + replay of the
        router's bounded frame window."""
        if self._stop.is_set():
            raise RuntimeError("router is closed")
        # anonymous requests on a single-model fleet resolve to that
        # model for SLO/admission/breaker accounting (replicas still
        # receive model=None and apply their own default)
        key = model
        if key is None:
            key = self._models[0] if len(self._models) == 1 else "_default"
        breaker = self._breaker(key)
        if not breaker.allow():
            self.telemetry.inc("shed_circuit")
            raise RouterShedError(
                f"circuit open for model {key!r} (replicas failing); "
                "shedding fast", breaker.retry_after_s())
        try:
            self._admission.admit(key)
        except ShedError:
            self.telemetry.inc("shed_admission")
            raise
        self.telemetry.inc("requests")
        # the model's p95 SLO budget is a deadline CEILING: it applies
        # even under the CLI surfaces' blanket timeout (which would
        # otherwise override it); an explicit tighter client timeout
        # still wins
        bounds = [b for b in (timeout_s, self._slo.get(key))
                  if b is not None]
        budget = min(bounds) if bounds else self._default_deadline_s
        req = _Request(model, x, deadline=time.monotonic() + budget,
                       key=key, trace=trace, session=session,
                       seq=seq if seq is None else int(seq))
        if session is not None:
            self._enqueue_session(req, breaker, key)
        else:
            self._pool.submit(self._dispatch, req, breaker, key)
        return req.future

    # -- request lifecycle -----------------------------------------------
    def _breaker(self, key: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(self._circuit_cfg)
            return b

    def _finish(self, req: _Request, key: str, *, result=None,
                error: BaseException | None = None) -> bool:
        """Resolve + bookkeep exactly once; -> whether THIS call won
        the resolve race (late hedges/duplicates get False)."""
        if not req.resolve(result, error):
            return False
        self._admission.release(key)
        if error is None:
            e2e = time.perf_counter() - req.t_submit
            self.telemetry.record_completed(e2e)
        elif isinstance(error, RouterShedError):
            self.telemetry.inc("shed_no_replica")
        elif isinstance(error, ShedError):
            # replica-side backpressure that survived the retry budget:
            # capacity exists but is saturated — not an availability gap
            self.telemetry.inc("shed_replica")
        else:
            self.telemetry.inc("failed")
        return True

    def _pick(self, tried: set[str]) -> _Slot | None:
        """Least-inflight READY slot, preferring ones not yet tried for
        this request; falls back to a tried slot only when nothing else
        is available (retrying a shed on the same replica later beats
        failing outright)."""
        with self._lock:
            ready = [s for s in self._slots if s.state == READY]
            fresh = [s for s in ready if s.sid not in tried]
            pool = fresh or ready
            if not pool:
                return None
            slot = min(pool, key=lambda s: (s.inflight, s.sid))
            slot.inflight += 1
            return slot

    def _dispatch(self, req: _Request, breaker: CircuitBreaker,
                  key: str) -> None:
        """Coordinate attempts for one request: launch, hedge on a slow
        primary, fail over on errors — until one attempt wins, the
        retry budget is spent, or the deadline passes."""
        outstanding: dict[Future, _Slot] = {}
        tried: set[str] = set()
        retries_left = self._max_retries
        hedges_left = 1 if self._hedge_after_s is not None else 0
        last_exc: BaseException | None = None
        failed_over = False
        try:
            while True:
                remaining = req.deadline - time.monotonic()
                if remaining <= 0:
                    self._finish(req, key, error=last_exc or TimeoutError(
                        "deadline expired before any replica answered"))
                    return
                if not outstanding:
                    slot = self._pick(tried)
                    if slot is None:
                        self._finish(req, key, error=(
                            last_exc if isinstance(last_exc, ShedError)
                            else RouterShedError(
                                "no replica available (all draining, "
                                "dead, or starting)",
                                round(2 * self._probe_interval_s, 3))))
                        return
                    if failed_over:
                        self.telemetry.inc("failovers")
                        failed_over = False
                    tried.add(slot.sid)
                    outstanding[self._attempt_pool.submit(
                        self._attempt, req, slot, breaker)] = slot
                hedge_ok = (hedges_left > 0 and len(outstanding) == 1
                            and len(tried) < self._slot_count())
                timeout = (min(remaining, self._hedge_after_s)
                           if hedge_ok else remaining)
                done, _pending = futures_wait(
                    set(outstanding), timeout=timeout,
                    return_when=FIRST_COMPLETED)
                if not done:
                    if hedge_ok:
                        slot = self._pick(tried)
                        if slot is not None:
                            hedges_left -= 1
                            self.telemetry.inc("hedges")
                            tried.add(slot.sid)
                            outstanding[self._attempt_pool.submit(
                                self._attempt, req, slot, breaker,
                                hedge=True)] = slot
                        else:
                            hedges_left = 0
                    continue
                for f in done:
                    outstanding.pop(f)
                    ok, payload = f.result()
                    if ok:
                        self._finish(req, key, result=payload)
                        return
                    last_exc = payload
                    if isinstance(payload, ReplicaDeadError):
                        failed_over = True  # counted when a retry launches
                if outstanding:
                    continue  # a hedge is still racing
                if isinstance(last_exc, ValueError) \
                        or retries_left <= 0:
                    # client errors never retry; budget exhausted fails
                    self._finish(req, key, error=last_exc)
                    return
                retries_left -= 1
        except Exception as e:  # coordinator bug: never strand the client
            self._finish(req, key, error=e)

    # -- stateful streams (serve/sessions.py) ----------------------------
    def _enqueue_session(self, req: _Request, breaker, key: str) -> None:
        """Append one frame to its stream's FIFO and ensure exactly one
        drain task runs per stream — frames of one session dispatch
        strictly in submission order, sessions stay parallel."""
        with self._lock:
            route = self._sessions.get(req.session)
            if route is None:
                route = self._sessions[req.session] = _SessionRoute(
                    req.session, self._session_replay_window)
            route.last_used = time.monotonic()
            route.queue.append((req, breaker, key))
            if not route.active:
                route.active = True
                self._pool.submit(self._drain_session, route)

    def _drain_session(self, route: _SessionRoute) -> None:
        while True:
            with self._lock:
                if not route.queue:
                    route.active = False
                    return
                req, breaker, key = route.queue.pop(0)
            if self._stop.is_set():
                self._finish(req, key,
                             error=RuntimeError("router is closed"))
                continue
            try:
                self._dispatch_stateful(route, req, breaker, key)
            except Exception as e:  # drain bug: never strand the client
                self._finish(req, key, error=e)

    def _pin_slot(self, route: _SessionRoute
                  ) -> tuple[_Slot | None, bool]:
        """The stream's sticky slot (inflight-incremented), hash-picking
        a fresh pin when none exists and MIGRATING (second return value)
        when the old pin is no longer routable."""
        with self._lock:
            ready = sorted((s for s in self._slots if s.state == READY),
                           key=lambda s: s.sid)
            if route.pin is not None:
                for s in ready:
                    if s.sid == route.pin:
                        s.inflight += 1
                        return s, False
            if not ready:
                return None, False
            # stable hash-pin: the same session lands on the same slot
            # id across router restarts (crc32, not PYTHONHASHSEED)
            slot = ready[zlib.crc32(route.sid.encode()) % len(ready)]
            migrated = route.pin is not None
            route.pin = slot.sid
            slot.inflight += 1
            return slot, migrated

    def _replay_window(self, route: _SessionRoute, slot: _Slot,
                       req: _Request, breaker) -> tuple[bool, bool]:
        """Re-send the buffered frame window (seq < current) to a fresh
        pin so it can rebuild state past its newest snapshot; the
        replica dedupes already-covered seqs idempotently. Returns
        (ok, reset_seen) — reset_seen propagates any state_reset a
        replayed frame declared, so the client-visible frame never
        hides a reset that happened during recovery."""
        with self._lock:
            frames = [(s, x) for s, x in route.window if s < req.seq]
        reset_seen = False
        for s, x in frames:
            remaining = req.deadline - time.monotonic()
            if remaining <= 0:
                return False, reset_seen
            replay = _Request(req.model, x,
                              deadline=req.deadline, key=req.key,
                              trace=req.trace, session=req.session,
                              seq=s)
            with self._lock:
                # pair the increment _attempt's finally will decrement
                slot.inflight += 1
            ok, payload = self._attempt(replay, slot, breaker)
            if not ok:
                return False, reset_seen
            if isinstance(payload, dict) and payload.get("state_reset"):
                reset_seen = True
        return True, reset_seen

    def _dispatch_stateful(self, route: _SessionRoute, req: _Request,
                           breaker, key: str) -> None:
        """Coordinate one stream frame: sticky attempt on the pin, and
        on pin death re-pin to a survivor + replay the frame window.
        NEVER hedges — a duplicate in-flight frame could double-apply a
        state update; retry safety comes from the replica's seq dedupe
        instead."""
        retries_left = self._max_retries
        last_exc: BaseException | None = None
        reset_seen = False
        while True:
            remaining = req.deadline - time.monotonic()
            if remaining <= 0:
                self._finish(req, key, error=last_exc or TimeoutError(
                    "deadline expired before any replica answered"))
                return
            slot, migrated = self._pin_slot(route)
            if slot is None:
                self._finish(req, key, error=(
                    last_exc if isinstance(last_exc, ShedError)
                    else RouterShedError(
                        "no replica available for pinned session",
                        round(2 * self._probe_interval_s, 3))))
                return
            if migrated:
                self.telemetry.inc("sessions_migrated")
                with self._lock:
                    n_replay = len(route.window)
                print(f"[router] session {route.sid} re-pinned to "
                      f"{slot.sid} (replaying {n_replay} frame(s))",
                      file=sys.stderr, flush=True)
                ok, rs = self._replay_window(route, slot, req, breaker)
                reset_seen = reset_seen or rs
                if not ok:
                    # replay target failed mid-recovery: undo nothing
                    # (replayed frames are idempotent), re-pin again
                    with self._lock:
                        slot.inflight = max(0, slot.inflight - 1)
                    if retries_left <= 0:
                        self._finish(req, key, error=last_exc
                                     or ReplicaDeadError(
                                         "replay target died"))
                        return
                    retries_left -= 1
                    continue
            ok, payload = self._attempt(req, slot, breaker)
            if ok:
                if isinstance(payload, dict):
                    if reset_seen:
                        payload["state_reset"] = True
                    if payload.get("state_reset"):
                        # the honesty counter: a DECLARED reset, never
                        # a silent one
                        self.telemetry.inc("session_resets")
                with self._lock:
                    route.window.append((req.seq, req.x))
                    route.last_used = time.monotonic()
                self._finish(req, key, result=payload)
                return
            last_exc = payload
            if isinstance(payload, ReplicaDeadError):
                # pin died: count the failover; the next loop pass
                # re-pins (and replays) onto a survivor
                self.telemetry.inc("failovers")
                continue
            if isinstance(payload, ValueError) or retries_left <= 0:
                self._finish(req, key, error=payload)
                return
            retries_left -= 1

    def _slot_count(self) -> int:
        with self._lock:
            return len([s for s in self._slots
                        if s.state in (READY, DRAINING)])

    def _attempt(self, req: _Request, slot: _Slot,
                 breaker: CircuitBreaker, hedge: bool = False):
        """One replica round-trip -> (ok, result_or_exc). Failure
        bookkeeping (breaker, dead-replica handling) happens here so a
        racing hedge's outcome is never lost."""
        t0 = time.perf_counter()
        try:
            if req.future.done():
                return False, RuntimeError("request already resolved")
            if self._injector is not None:
                delay = self._injector.check_replica_slow()
                if delay:
                    self._stop.wait(delay)
                if self._injector.check_replica_kill():
                    slot.replica.kill()
            remaining = req.deadline - time.monotonic()
            if remaining <= 0:
                return False, TimeoutError("deadline expired")
            # the router half of the distributed request timeline: the
            # span shares the request's trace id with the replica-side
            # queue/device spans, so trace_merge can draw the flow
            # router attempt -> replica execution (no-op unless the
            # tracer is active)
            kw = {}
            if req.session is not None:
                # stateful frame: session/seq ride to the replica (only
                # passed when set, so bare test doubles keep working)
                kw = {"session": req.session, "seq": req.seq}
            with span("router_attempt", cat="router",
                      args={"trace": req.trace, "replica": slot.sid,
                            "model": req.key,
                            **({"session": req.session}
                               if req.session is not None else {}),
                            **({"hedge": True} if hedge else {})}):
                result = slot.replica.request(
                    req.model, req.x, timeout_s=remaining,
                    trace=req.trace, **kw)
        except ReplicaDeadError as e:
            breaker.record_failure()
            self._on_replica_dead(slot, str(e))
            return False, e
        except ShedError as e:
            return False, e  # overload is not a breaker failure
        except ValueError as e:
            return False, e  # client error: no breaker, no retry
        except TimeoutError as e:
            breaker.record_failure()
            return False, e
        except Exception as e:
            breaker.record_failure()
            return False, e
        else:
            breaker.record_success()
            dt = time.perf_counter() - t0
            self.telemetry.record_attempt(dt)
            # the admission EWMA wants per-row SERVICE time (its shed
            # estimate is depth x row_s): feed the replica round-trip,
            # not the request's e2e — e2e already contains queue wait,
            # and depth x e2e double-counts it into a shed spiral
            self._admission.observe_batch(dt, 1)
            if hedge and self._finish(req, req.key, result=result):
                # the duplicate beat the primary: first response wins
                # (one resolve, one set of bookkeeping — _finish's)
                self.telemetry.inc("hedge_wins")
            return True, result
        finally:
            with self._lock:
                slot.inflight = max(0, slot.inflight - 1)

    # -- supervision -----------------------------------------------------
    def _on_replica_dead(self, slot: _Slot, why: str) -> None:
        with self._lock:
            if slot.state in (DEAD, STOPPED):
                return
            slot.state = DEAD
        self.telemetry.inc("replica_deaths")
        print(f"[router] replica {slot.sid} dead: {why}", file=sys.stderr, flush=True)

    def _spawn_slot_async(self, generation: int | None = None
                          ) -> threading.Thread:
        """Start a fresh replica in a background thread (process
        replicas take seconds to boot — never block routing on one)."""
        with self._lock:
            self._gen += 1
            gen = self._gen if generation is None else generation
            sid = f"r{gen}"
            slot = _Slot(sid, None, STARTING, gen)
            self._slots.append(slot)

        def boot():
            try:
                replica = self._factory(sid)
                slot.replica = replica
                replica.start()
            except Exception as e:
                print(f"[router] replica {sid} failed to start: {e}",
                      file=sys.stderr, flush=True)
                slot.state = DEAD
                return
            # a boot finishing during close() still lands in _slots, so
            # close()'s stop sweep shuts it down right after
            slot.state = READY

        t = threading.Thread(target=boot, name=f"router-boot-{sid}")
        t.start()
        with self._lock:
            self._respawners.append(t)
        return t

    def _ready_slots(self) -> list[_Slot]:
        with self._lock:
            return [s for s in self._slots if s.state == READY]

    def _probe_loop(self) -> None:
        """Health-gate + supervise + autoscale, every probe interval.
        Sleeps through the stop event (jaxlint JX113: loop waits must
        stay stop-responsive) so close() never blocks on a tick."""
        while not self._stop.wait(self._probe_interval_s):
            self._probe_once()
            self._reap_and_respawn()
            self._publish_signals_and_autoscale()
            self._gc_respawners()

    def _probe_once(self) -> None:
        with self._lock:
            slots = [s for s in self._slots
                     if s.state in (READY, DRAINING, RETIRING)]
        for slot in slots:
            try:
                health = slot.replica.probe()
            except ReplicaDeadError as e:
                self._on_replica_dead(slot, str(e))
                continue
            except Exception as e:
                self._on_replica_dead(slot, f"probe error: {e}")
                continue
            ok = health.get("status") == "ok"
            if slot.state == RETIRING:
                if slot.inflight == 0:
                    self._retire(slot)
            elif ok and slot.state == DRAINING:
                slot.state = READY
                print(f"[router] replica {slot.sid} healthy again; "
                      "undrained", file=sys.stderr, flush=True)
            elif not ok and slot.state == READY:
                slot.state = DRAINING
                print(f"[router] replica {slot.sid} degraded "
                      f"({health.get('status')}); draining", file=sys.stderr, flush=True)

    def _retire(self, slot: _Slot) -> None:
        th = threading.Thread(target=self._stop_replica, args=(slot,),
                              name=f"router-retire-{slot.sid}")
        th.start()
        with self._lock:
            self._respawners.append(th)
            if slot in self._slots:
                self._slots.remove(slot)
        self.telemetry.inc("scale_downs")
        print(f"[router] replica {slot.sid} drained and retired "
              f"(target {self._target})", file=sys.stderr, flush=True)

    def _reap_and_respawn(self) -> None:
        """Respawn toward the target count with capped backoff between
        waves. The backoff is a timestamp gate, never a sleep — the
        probe loop must keep health-gating the survivors while a
        crash-looping replica waits out its window."""
        now = time.monotonic()
        with self._lock:
            dead = [s for s in self._slots if s.state == DEAD]
            for s in dead:
                self._slots.remove(s)
            alive = len([s for s in self._slots
                         if s.state in (READY, DRAINING, STARTING)])
            missing = self._target - alive
        for s in dead:
            # make sure the corpse is actually dead before forgetting
            # it: a false death verdict (probe timeout under load) on a
            # still-running child would otherwise leak a zombie process
            # competing with its own replacement forever. kill() is
            # idempotent and a no-op on an already-gone process.
            t = threading.Thread(target=self._kill_replica, args=(s,),
                                 name=f"router-reap-{s.sid}")
            t.start()
            with self._lock:
                self._respawners.append(t)
        if dead:
            # fresh deaths push the next respawn wave out and escalate
            self._respawn_not_before = max(self._respawn_not_before,
                                           now + self._backoff)
            self._backoff = min(self._backoff * 2,
                                self._restart_backoff_max_s)
        if missing <= 0:
            if not dead:
                self._backoff = self._restart_backoff_s  # healthy: reset
            return
        if now < self._respawn_not_before:
            return
        for _ in range(missing):
            self.telemetry.inc("replica_restarts")
            self._spawn_slot_async()

    def _gc_respawners(self) -> None:
        with self._lock:
            self._respawners = [t for t in self._respawners
                                if t.is_alive()]

    # -- signals + autoscaling -------------------------------------------
    def _publish_signals_and_autoscale(self) -> None:
        now = time.monotonic()
        tel = self.telemetry
        with self._lock:
            slots = [s for s in self._slots
                     if s.state in (READY, DRAINING)]
            ready_n = len([s for s in slots if s.state == READY])
        queue_p95 = 0.0
        sheds = float(tel.shed_admission + tel.shed_circuit
                      + tel.shed_no_replica)
        crashes = 0.0
        for slot in slots:
            try:
                st = slot.replica.stats()
            except Exception:
                continue
            t = st.get("telemetry", {})
            queue_p95 = max(queue_p95,
                            t.get("queue_wait", {}).get("p95_ms", 0.0))
            sheds += float(t.get("shed", 0))
            crashes += float(t.get("dispatcher_crashes", 0))
        dt = max(1e-6, now - self._last_signal_t)
        shed_rate = max(0.0, sheds - self._last_shed_totals) / dt
        self._last_shed_totals = sheds
        self._last_signal_t = now
        tel.replicas_ready.set(ready_n)
        tel.replicas_target.set(self._target)
        tel.queue_wait_p95_ms.set(queue_p95)
        tel.shed_rate_per_s.set(shed_rate)
        tel.dispatcher_crashes.set(crashes)
        if now >= self._flight_note_due:
            # the serving-side flight-recorder cadence: a counter-delta
            # note every ~2s turns the crash black box into "what the
            # router was doing, tick by tick, right before the end"
            self._flight_note_due = now + 2.0
            from deepvision_tpu.obs.distributed import get_flight_recorder

            rec = get_flight_recorder()
            if rec is not None:
                rec.note("probe", replicas_ready=ready_n)
        if self._autoscaler is None or now < self._autoscale_due:
            return
        self._autoscale_due = now + self._autoscale_cfg.interval_s
        # the autoscaler reads the published obs-registry signals BY
        # NAME — the same numbers a human sees on GET /metrics
        reg = tel.registry
        new_target = self._autoscaler.tick(
            queue_p95_ms=reg.value_of("router_queue_wait_p95_ms"),
            shed_rate_per_s=reg.value_of("router_shed_rate_per_s"),
            dispatcher_crashes=reg.value_of("router_dispatcher_crashes"),
            target=self._target)
        if new_target > self._target:
            with self._lock:
                self._target = new_target
            tel.inc("scale_ups")
            print(f"[router] autoscale up -> {new_target} "
                  f"(queue_p95={queue_p95:.1f}ms "
                  f"shed_rate={shed_rate:.2f}/s)", file=sys.stderr, flush=True)
            self._spawn_slot_async()
        elif new_target < self._target:
            with self._lock:
                self._target = new_target
                ready = [s for s in self._slots if s.state == READY]
                victim = (min(ready, key=lambda s: (s.inflight, s.sid))
                          if len(ready) > 1 else None)
                if victim is not None:
                    victim.state = RETIRING
                else:
                    self._target = new_target + 1  # nothing drainable
            if victim is not None:
                print(f"[router] autoscale down -> {new_target} "
                      f"(draining {victim.sid})", file=sys.stderr, flush=True)
        tel.replicas_target.set(self._target)

    # -- introspection ---------------------------------------------------
    def metrics_children(self) -> dict[str, dict]:
        """Scrape every live replica's typed registry dump keyed by
        slot id — the federation input. Children are scraped
        CONCURRENTLY so one wedged replica costs the surface a single
        scrape timeout, not one per wedged child — the fleet's metrics
        must stay up precisely when replicas are misbehaving. A
        replica that fails the scrape (mid-restart, mid-drain) is
        skipped, not fatal: the fleet surface degrades to the
        reachable children."""
        with self._lock:
            slots = [s for s in self._slots
                     if s.state in (READY, DRAINING) and
                     s.replica is not None]
        children: dict[str, dict] = {}
        if not slots:
            return children
        with ThreadPoolExecutor(
                max_workers=len(slots),
                thread_name_prefix="dvtpu-metrics-scrape") as pool:
            pending = {s.sid: pool.submit(s.replica.metrics_dump)
                       for s in slots}
            for sid, fut in pending.items():
                try:
                    children[sid] = fut.result()
                except Exception:
                    continue
        return children

    def render_metrics(self) -> str:
        """The fleet's single aggregated Prometheus surface
        (obs/distributed.py federation): the router's own ``router_*``
        families plus every replica's ``serve_*`` families labelled
        ``{replica="rN"}``, with exact counter sums and
        reservoir-merged histogram quantiles — one scrape describes
        the whole fleet."""
        return render_federated(self.metrics_children(),
                                own=self.telemetry.registry,
                                label="replica", own_label="router")

    def health(self) -> dict:
        """Fleet liveness for ``/healthz``: ok while >= 1 replica is
        READY; 503 (with a re-probe hint) while the whole fleet is
        down/draining — the same contract a replica's own /healthz has,
        one level up."""
        ready = len(self._ready_slots())
        with self._lock:
            target = self._target
        status = "ok" if ready > 0 else "recovering"
        out = {
            "status": status,
            "replicas_ready": ready,
            "replicas_target": target,
        }
        if status != "ok":
            out["retry_after_s"] = round(2 * self._probe_interval_s, 3)
        return out

    def stats(self) -> dict:
        with self._lock:
            replicas = [{
                "id": s.sid,
                "state": s.state,
                "inflight": s.inflight,
            } for s in self._slots]
            target = self._target
            sessions = {
                "live": len(self._sessions),
                "replay_window": self._session_replay_window,
                "pins": {r.sid: r.pin
                         for r in self._sessions.values()},
            }
        return {
            "models": sorted(self._models),
            "replicas": replicas,
            "target_replicas": target,
            "slo_budgets_s": dict(self._slo),
            "queue": self._admission.stats(),
            "breakers": {k: b.snapshot()
                         for k, b in self._breakers.items()},
            "health": self.health(),
            "sessions": sessions,
            "telemetry": self.telemetry.snapshot(),
        }

    def summary_line(self) -> str:
        return self.telemetry.summary_line()
