"""Multi-tenant weight residency + zero-drop hot-swap.

N registry models / StableHLO artifacts share one replica's HBM. The
mechanism underneath everything is the **weights edition**: a small
mutable cell holding one generation of a tenant's weights plus its
content fingerprint. Compiled runners capture the edition object at
compile time and read ``edition.variables`` at *call* time
(``pipeline.ModelStage.variables_ref``), which buys both halves of the
tenancy story at once:

- **LRU residency**: evicting a cold tenant replaces
  ``edition.variables`` with host copies — the edition held the only
  strong refs to the device buffers, so HBM is actually freed even
  though compiled executables for that tenant stay cached.
  Re-materializing is one ``device_put`` back into the same edition:
  no recompile, and every cached runner sees the device weights again.
- **Zero-drop hot-swap**: a swap builds a NEW edition and pre-compiles
  the whole bucket ladder against it off the dispatch path, then flips
  the tenant's edition pointer atomically between batches. Old runners
  keep their compile-time edition, so requests already queued against
  the pre-swap executables drain on the pre-swap weights — no drops,
  no torn weight/executable pairing. The compile-cache key carries the
  fingerprint, so the flip is a cache *miss* into the freshly
  installed entries, never a stale hit.

A swap whose new weights fingerprint equals the current one (a
retried swap, re-restoring the same checkpoint) is a loud no-op:
running the flip would drop the live runners it just installed, since
old and new key identically.

Two kinds of HBM sit outside the budget's reach and are surfaced in
:meth:`TenancyManager.stats` instead of silently under-counted:
**baked** tenants (every executable warmed from the artifact store
with weights baked in as program constants — the unused edition
device copy is released to host) and **retired** editions (pre-swap
weight generations still pinned by compiled runners, e.g. a pipeline
stage serving its compile-time weights until re-registered; these DO
count in :meth:`TenancyManager.resident_bytes` for as long as they
are held).

Per-tenant isolation (admission quotas, SLO classes, shed accounting)
lives in ``admission.AdmissionController`` — the engine and
``FleetRouter`` thread tenant maps through it so one noisy tenant
sheds alone.
"""

from __future__ import annotations

import hashlib
import threading
import weakref
from collections import OrderedDict
from typing import Any

import numpy as np

__all__ = ["TenancyManager", "WeightsEdition", "fingerprint_variables",
           "tree_nbytes"]


def fingerprint_variables(variables) -> str:
    """Content hash of a weights pytree (structure + leaf bytes),
    truncated sha256. Content-derived on purpose: a respawned replica
    restoring the same checkpoint computes the same fingerprint, so
    artifact-store keys match across process generations. ``None``
    (StableHLO artifacts: weights baked into the program) hashes to
    the sentinel ``"artifact"``."""
    if variables is None:
        return "artifact"
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def tree_nbytes(variables) -> int:
    import jax

    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree_util.tree_leaves(variables))


class WeightsEdition:
    """One generation of a tenant's weights. Identity is the unit of
    hot-swap isolation: runners compiled against this edition read
    ``variables`` through it forever, so mutating the cell (evict /
    re-materialize) retargets every cached executable at once, while a
    swap — a *new* edition — retargets none of them."""

    # __weakref__: retired editions (pre-swap generations still pinned
    # by compiled runners, e.g. a pipeline stage) are tracked weakly so
    # stats can report their HBM for exactly as long as it is held
    __slots__ = ("variables", "fingerprint", "nbytes", "resident",
                 "__weakref__")

    def __init__(self, variables, fingerprint: str, nbytes: int,
                 *, resident: bool):
        self.variables = variables
        self.fingerprint = fingerprint
        self.nbytes = nbytes
        self.resident = resident

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"WeightsEdition({self.fingerprint}, "
                f"{self.nbytes}B, resident={self.resident})")


class TenancyManager:
    """LRU weight residency + hot-swap for one engine's tenants.

    ``budget_bytes`` caps the summed resident weight bytes; the
    least-recently-dispatched tenants beyond it are evicted to host.
    ``None`` disables eviction (every tenant stays resident — the
    pre-tenancy behavior). All counters are grep-stable via
    :meth:`summary_line`.
    """

    def __init__(self, mesh, *, budget_bytes: int | None = None,
                 log=print):
        self._mesh = mesh
        self._budget = budget_bytes
        self._log = log
        self._lock = threading.RLock()
        # serializes swaps only: ladder pre-compiles are slow and must
        # not hold the residency lock the dispatcher takes per batch
        self._swap_lock = threading.Lock()
        self._tenants: dict[str, Any] = {}
        self._lru: OrderedDict[str, None] = OrderedDict()
        # tenants whose ENTIRE ladder came off the artifact store with
        # weights baked in as program constants: name -> estimated
        # baked bytes. Nothing reads their edition at call time, so
        # they sit outside LRU residency (there is nothing a budget
        # eviction could free) but their HBM is surfaced in stats().
        self._baked: dict[str, int] = {}
        # swapped-out editions possibly still pinned by compiled
        # runners (pipeline DAG stages keep their compile-time
        # edition until re-registered): (tenant, weakref) pairs,
        # pruned as the last runner over each edition is released
        self._retired: list[tuple[str, weakref.ref]] = []
        self.swaps = 0
        self.evictions = 0
        self.rematerializations = 0

    # -- registration -----------------------------------------------------
    def adopt(self, served) -> None:
        """Register a tenant: fingerprint its (host) weights, stage
        them onto the mesh, and hang a :class:`WeightsEdition` off the
        model so every runner compiled from here on reads weights
        through the edition (``ServedModel.as_stage`` threads it)."""
        with self._lock:
            if served.name in self._tenants:
                return
            self._tenants[served.name] = served
            if served.variables is None:
                return  # artifact tenant: weights live in the program
            fp = served.weights_fingerprint()
            ed = WeightsEdition(
                self._stage_weights(served.variables), fp,
                tree_nbytes(served.variables), resident=True)
            served.edition = ed
            served.variables = ed.variables
            self._lru[served.name] = None
            self._evict_over_budget(protect=served.name)

    def _stage_weights(self, variables):
        """One replicated ``device_put`` of a whole weights pytree —
        the residency manager is the ONE place weights cross to the
        device (JX129 polices strays in dispatch loops)."""
        import jax

        from deepvision_tpu.core.mesh import replicated_sharding

        return jax.device_put(variables, replicated_sharding(self._mesh))

    # -- residency --------------------------------------------------------
    def ensure_resident(self, name: str) -> None:
        """Dispatch-path hook: touch the tenant's LRU slot and
        re-materialize its weights if a prior eviction moved them to
        host. Cheap when already resident (dict touch under lock)."""
        with self._lock:
            served = self._tenants.get(name)
            if served is None or served.edition is None \
                    or name in self._baked:
                # baked tenants: every executable carries its weights
                # as constants — re-staging the edition copy would be
                # pure HBM waste
                return
            if not served.edition.resident:
                self._rematerialize(served)
            self._lru[name] = None
            self._lru.move_to_end(name)
            self._evict_over_budget(protect=name)

    def _rematerialize(self, served) -> None:
        ed = served.edition
        ed.variables = self._stage_weights(ed.variables)
        served.variables = ed.variables
        ed.resident = True
        self.rematerializations += 1
        self._log(f"[tenancy] rematerialized {served.name} "
                  f"({ed.nbytes}B)", flush=True)

    def evict(self, name: str) -> bool:
        """Move one tenant's weights to host. The edition held the
        only strong refs to the device buffers (runners read through
        it at call time, and a batch mid-flight keeps its own ref for
        the call's duration), so this actually frees HBM while every
        compiled executable stays warm in the cache."""
        with self._lock:
            served = self._tenants.get(name)
            if (served is None or served.edition is None
                    or not served.edition.resident):
                return False
            import jax

            ed = served.edition
            ed.variables = jax.tree_util.tree_map(
                lambda a: np.asarray(a), ed.variables)
            served.variables = ed.variables
            ed.resident = False
            self._lru.pop(name, None)
            self.evictions += 1
            self._log(f"[tenancy] evicted {name} ({ed.nbytes}B) to host",
                      flush=True)
            return True

    def release_to_baked(self, served, n_programs: int) -> None:
        """Take a tenant whose ENTIRE bucket ladder was warmed from
        the artifact store out of edition residency. Store blobs are
        serialized programs with the weights baked in as constants —
        no runner reads the edition at call time — so the edition's
        separate device copy is freed to host and the tenant leaves
        the LRU (a budget eviction could not reclaim baked constants
        anyway). The baked copies' HBM (~weights bytes × programs) is
        recorded so ``stats()`` reports what the residency budget
        cannot govern instead of silently under-counting. A later
        hot-swap pre-compiles edition-backed runners and returns the
        tenant to normal residency management."""
        with self._lock:
            ed = getattr(served, "edition", None)
            if ed is None:
                return
            if ed.resident:
                import jax

                ed.variables = jax.tree_util.tree_map(
                    lambda a: np.asarray(a), ed.variables)
                served.variables = ed.variables
                ed.resident = False
            self._lru.pop(served.name, None)
            self._baked[served.name] = ed.nbytes * n_programs
            self._log(
                f"[tenancy] {served.name}: all {n_programs} executables "
                f"store-warmed (weights baked in); released edition "
                f"device copy ({ed.nbytes}B), baked "
                f"~{ed.nbytes * n_programs}B outside residency budget",
                flush=True)

    def resident_bytes(self) -> int:
        """Device bytes of weight editions: every current resident
        edition plus retired (swapped-out) editions still pinned by
        live runners. Baked store-warmed programs are outside the
        budget's reach and accounted separately
        (``stats()['baked_bytes']``)."""
        with self._lock:
            current = sum(
                t.edition.nbytes for t in self._tenants.values()
                if t.edition is not None and t.edition.resident)
            pinned = sum(ed.nbytes for _n, ed in self._live_retired()
                         if ed.resident)
            return current + pinned

    def _live_retired(self) -> list[tuple[str, Any]]:
        """(tenant, edition) for swapped-out editions some compiled
        runner still holds; dead weakrefs prune on the way past.
        Caller must hold ``_lock``."""
        kept, out = [], []
        for name, ref in self._retired:
            ed = ref()
            if ed is not None:
                kept.append((name, ref))
                out.append((name, ed))
        self._retired = kept
        return out

    def _evict_over_budget(self, *, protect: str | None = None) -> None:
        if self._budget is None:
            return
        while self.resident_bytes() > self._budget:
            victim = next((n for n in self._lru if n != protect), None)
            if victim is None:
                break  # the protected tenant alone exceeds the budget
            self.evict(victim)

    # -- hot-swap ---------------------------------------------------------
    def swap(self, served, new_variables, *, ladder, mesh, cache,
             key_fn) -> dict:
        """Zero-drop weight hot-swap. Everything slow — staging the
        new weights, pre-compiling every ladder bucket — happens on
        the caller's thread against a NEW edition while the dispatcher
        keeps serving the old one. The flip is an atomic pointer swap
        under the residency lock: install the new executables in the
        cache first, then repoint the tenant, so the dispatcher's
        per-batch (fingerprint -> runner) read always pairs weights
        with the executable compiled for them. Old runners keep their
        compile-time edition and drain untouched."""
        import dataclasses

        with self._swap_lock:
            old_fp = served.weights_fingerprint()
            fp = fingerprint_variables(new_variables)
            if fp == old_fp:
                # retried swap / workdir= re-restore of the same
                # checkpoint: the installed ladder already pairs with
                # exactly these bytes. Re-running the flip would be
                # churn, and dropping the "old" fingerprint would
                # delete the LIVE runners (old == new) — on a frozen
                # cache every later request would then die on the
                # miss tripwire. No-op, loudly.
                self._log(f"[tenancy] swap {served.name}: fingerprint "
                          f"{fp} unchanged; no-op", flush=True)
                return {"model": served.name, "fingerprint": fp,
                        "old_fingerprint": old_fp,
                        "buckets": [int(b) for b in ladder],
                        "dropped_executables": 0, "unchanged": True}
            new_ed = WeightsEdition(
                self._stage_weights(new_variables), fp,
                tree_nbytes(new_variables), resident=True)
            # shadow model: same surface, new edition — what the
            # ladder pre-compiles and the store exports against
            shadow = dataclasses.replace(
                served, variables=new_ed.variables, edition=new_ed,
                _fingerprint=fp, _direct=None)
            runners = {}
            for bucket in ladder:
                runners[key_fn(shadow, bucket)] = shadow.compile_for(
                    bucket, mesh)
            with self._lock:
                if served.edition is not None:
                    # the old edition may outlive the flip (pipeline
                    # DAG runners compiled against it keep serving it
                    # until re-registered): track it weakly so
                    # stats/resident_bytes keep counting that HBM for
                    # as long as some runner pins it
                    self._retired.append(
                        (served.name, weakref.ref(served.edition)))
                for key, runner in runners.items():
                    cache.install(key, runner)
                served.edition = new_ed
                served.variables = new_ed.variables
                served._fingerprint = fp
                # edition-backed from here on, even if the pre-swap
                # ladder was baked store programs
                self._baked.pop(served.name, None)
                self._lru[served.name] = None
                self._lru.move_to_end(served.name)
                self.swaps += 1
            # hygiene, outside the dispatch-path lock: executables for
            # the old fingerprint can never be *hit* again (the key
            # changed), so drop them; a batch mid-flight holds its own
            # runner reference and drains regardless
            dropped = cache.drop_where(
                lambda k: k[0] == served.name and len(k) > 3
                and k[3] == old_fp)
            with self._lock:
                # under the residency lock: the dispatcher mutates
                # _lru concurrently in ensure_resident, and the
                # eviction scan must not see a torn view
                self._evict_over_budget(protect=served.name)
            self._log(f"[tenancy] swapped {served.name}: {old_fp} -> "
                      f"{fp} ({len(runners)} buckets, "
                      f"{dropped} stale executables dropped)", flush=True)
            return {"model": served.name, "fingerprint": fp,
                    "old_fingerprint": old_fp,
                    "buckets": [int(b) for b in ladder],
                    "dropped_executables": int(dropped)}

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "tenants": sorted(self._tenants),
                "resident": [n for n, t in sorted(self._tenants.items())
                             if t.edition is not None
                             and t.edition.resident],
                "resident_bytes": self.resident_bytes(),
                "budget_bytes": self._budget,
                # HBM the budget cannot govern, surfaced instead of
                # silently under-counted: store-warmed tenants whose
                # weights are baked into their programs, and
                # swapped-out editions still pinned by live runners
                # (e.g. a pipeline stage serving its compile-time
                # weights until re-registered)
                "baked": sorted(self._baked),
                "baked_bytes": sum(self._baked.values()),
                "retired_pinned": [
                    {"tenant": n, "fingerprint": ed.fingerprint,
                     "nbytes": ed.nbytes}
                    for n, ed in self._live_retired() if ed.resident],
                "swaps": self.swaps,
                "evictions": self.evictions,
                "rematerializations": self.rematerializations,
            }

    def summary_line(self) -> str:
        """Grep-stable exit line (``serve.py`` prints it at shutdown;
        ``make swap-smoke`` asserts on it)."""
        return (f"[tenancy] swaps={self.swaps} "
                f"evictions={self.evictions} "
                f"rematerializations={self.rematerializations} "
                f"resident={len(self.stats()['resident'])}"
                f"/{len(self._tenants)}")
