"""Multi-tenant weight residency + zero-drop hot-swap.

N registry models / StableHLO artifacts share one replica's HBM. The
mechanism underneath everything is the **weights edition**: a small
mutable cell holding one generation of a tenant's weights plus its
content fingerprint. Compiled runners capture the edition object at
compile time and read ``edition.variables`` at *call* time
(``pipeline.ModelStage.variables_ref``), which buys both halves of the
tenancy story at once:

- **LRU residency**: evicting a cold tenant replaces
  ``edition.variables`` with host copies — the edition held the only
  strong refs to the device buffers, so HBM is actually freed even
  though compiled executables for that tenant stay cached.
  Re-materializing is one ``device_put`` back into the same edition:
  no recompile, and every cached runner sees the device weights again.
- **Zero-drop hot-swap**: a swap builds a NEW edition and pre-compiles
  the whole bucket ladder against it off the dispatch path, then flips
  the tenant's edition pointer atomically between batches. Old runners
  keep their compile-time edition, so requests already queued against
  the pre-swap executables drain on the pre-swap weights — no drops,
  no torn weight/executable pairing. The compile-cache key carries the
  fingerprint, so the flip is a cache *miss* into the freshly
  installed entries, never a stale hit.

Per-tenant isolation (admission quotas, SLO classes, shed accounting)
lives in ``admission.AdmissionController`` — the engine and
``FleetRouter`` thread tenant maps through it so one noisy tenant
sheds alone.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any

import numpy as np

__all__ = ["TenancyManager", "WeightsEdition", "fingerprint_variables",
           "tree_nbytes"]


def fingerprint_variables(variables) -> str:
    """Content hash of a weights pytree (structure + leaf bytes),
    truncated sha256. Content-derived on purpose: a respawned replica
    restoring the same checkpoint computes the same fingerprint, so
    artifact-store keys match across process generations. ``None``
    (StableHLO artifacts: weights baked into the program) hashes to
    the sentinel ``"artifact"``."""
    if variables is None:
        return "artifact"
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        a = np.asarray(leaf)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


def tree_nbytes(variables) -> int:
    import jax

    return sum(int(np.asarray(leaf).nbytes)
               for leaf in jax.tree_util.tree_leaves(variables))


class WeightsEdition:
    """One generation of a tenant's weights. Identity is the unit of
    hot-swap isolation: runners compiled against this edition read
    ``variables`` through it forever, so mutating the cell (evict /
    re-materialize) retargets every cached executable at once, while a
    swap — a *new* edition — retargets none of them."""

    __slots__ = ("variables", "fingerprint", "nbytes", "resident")

    def __init__(self, variables, fingerprint: str, nbytes: int,
                 *, resident: bool):
        self.variables = variables
        self.fingerprint = fingerprint
        self.nbytes = nbytes
        self.resident = resident

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"WeightsEdition({self.fingerprint}, "
                f"{self.nbytes}B, resident={self.resident})")


class TenancyManager:
    """LRU weight residency + hot-swap for one engine's tenants.

    ``budget_bytes`` caps the summed resident weight bytes; the
    least-recently-dispatched tenants beyond it are evicted to host.
    ``None`` disables eviction (every tenant stays resident — the
    pre-tenancy behavior). All counters are grep-stable via
    :meth:`summary_line`.
    """

    def __init__(self, mesh, *, budget_bytes: int | None = None,
                 log=print):
        self._mesh = mesh
        self._budget = budget_bytes
        self._log = log
        self._lock = threading.RLock()
        # serializes swaps only: ladder pre-compiles are slow and must
        # not hold the residency lock the dispatcher takes per batch
        self._swap_lock = threading.Lock()
        self._tenants: dict[str, Any] = {}
        self._lru: OrderedDict[str, None] = OrderedDict()
        self.swaps = 0
        self.evictions = 0
        self.rematerializations = 0

    # -- registration -----------------------------------------------------
    def adopt(self, served) -> None:
        """Register a tenant: fingerprint its (host) weights, stage
        them onto the mesh, and hang a :class:`WeightsEdition` off the
        model so every runner compiled from here on reads weights
        through the edition (``ServedModel.as_stage`` threads it)."""
        with self._lock:
            if served.name in self._tenants:
                return
            self._tenants[served.name] = served
            if served.variables is None:
                return  # artifact tenant: weights live in the program
            fp = served.weights_fingerprint()
            ed = WeightsEdition(
                self._stage_weights(served.variables), fp,
                tree_nbytes(served.variables), resident=True)
            served.edition = ed
            served.variables = ed.variables
            self._lru[served.name] = None
            self._evict_over_budget(protect=served.name)

    def _stage_weights(self, variables):
        """One replicated ``device_put`` of a whole weights pytree —
        the residency manager is the ONE place weights cross to the
        device (JX129 polices strays in dispatch loops)."""
        import jax

        from deepvision_tpu.core.mesh import replicated_sharding

        return jax.device_put(variables, replicated_sharding(self._mesh))

    # -- residency --------------------------------------------------------
    def ensure_resident(self, name: str) -> None:
        """Dispatch-path hook: touch the tenant's LRU slot and
        re-materialize its weights if a prior eviction moved them to
        host. Cheap when already resident (dict touch under lock)."""
        with self._lock:
            served = self._tenants.get(name)
            if served is None or served.edition is None:
                return
            if not served.edition.resident:
                self._rematerialize(served)
            self._lru[name] = None
            self._lru.move_to_end(name)
            self._evict_over_budget(protect=name)

    def _rematerialize(self, served) -> None:
        ed = served.edition
        ed.variables = self._stage_weights(ed.variables)
        served.variables = ed.variables
        ed.resident = True
        self.rematerializations += 1
        self._log(f"[tenancy] rematerialized {served.name} "
                  f"({ed.nbytes}B)", flush=True)

    def evict(self, name: str) -> bool:
        """Move one tenant's weights to host. The edition held the
        only strong refs to the device buffers (runners read through
        it at call time, and a batch mid-flight keeps its own ref for
        the call's duration), so this actually frees HBM while every
        compiled executable stays warm in the cache."""
        with self._lock:
            served = self._tenants.get(name)
            if (served is None or served.edition is None
                    or not served.edition.resident):
                return False
            import jax

            ed = served.edition
            ed.variables = jax.tree_util.tree_map(
                lambda a: np.asarray(a), ed.variables)
            served.variables = ed.variables
            ed.resident = False
            self._lru.pop(name, None)
            self.evictions += 1
            self._log(f"[tenancy] evicted {name} ({ed.nbytes}B) to host",
                      flush=True)
            return True

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(
                t.edition.nbytes for t in self._tenants.values()
                if t.edition is not None and t.edition.resident)

    def _evict_over_budget(self, *, protect: str | None = None) -> None:
        if self._budget is None:
            return
        while self.resident_bytes() > self._budget:
            victim = next((n for n in self._lru if n != protect), None)
            if victim is None:
                break  # the protected tenant alone exceeds the budget
            self.evict(victim)

    # -- hot-swap ---------------------------------------------------------
    def swap(self, served, new_variables, *, ladder, mesh, cache,
             key_fn) -> dict:
        """Zero-drop weight hot-swap. Everything slow — staging the
        new weights, pre-compiling every ladder bucket — happens on
        the caller's thread against a NEW edition while the dispatcher
        keeps serving the old one. The flip is an atomic pointer swap
        under the residency lock: install the new executables in the
        cache first, then repoint the tenant, so the dispatcher's
        per-batch (fingerprint -> runner) read always pairs weights
        with the executable compiled for them. Old runners keep their
        compile-time edition and drain untouched."""
        import dataclasses

        with self._swap_lock:
            old_fp = served.weights_fingerprint()
            fp = fingerprint_variables(new_variables)
            new_ed = WeightsEdition(
                self._stage_weights(new_variables), fp,
                tree_nbytes(new_variables), resident=True)
            # shadow model: same surface, new edition — what the
            # ladder pre-compiles and the store exports against
            shadow = dataclasses.replace(
                served, variables=new_ed.variables, edition=new_ed,
                _fingerprint=fp, _direct=None)
            runners = {}
            for bucket in ladder:
                runners[key_fn(shadow, bucket)] = shadow.compile_for(
                    bucket, mesh)
            with self._lock:
                for key, runner in runners.items():
                    cache.install(key, runner)
                served.edition = new_ed
                served.variables = new_ed.variables
                served._fingerprint = fp
                self._lru[served.name] = None
                self._lru.move_to_end(served.name)
                self.swaps += 1
            # hygiene, outside the dispatch-path lock: executables for
            # the old fingerprint can never be *hit* again (the key
            # changed), so drop them; a batch mid-flight holds its own
            # runner reference and drains regardless
            dropped = cache.drop_where(
                lambda k: k[0] == served.name and len(k) > 3
                and k[3] == old_fp)
            self._evict_over_budget(protect=served.name)
            self._log(f"[tenancy] swapped {served.name}: {old_fp} -> "
                      f"{fp} ({len(runners)} buckets, "
                      f"{dropped} stale executables dropped)", flush=True)
            return {"model": served.name, "fingerprint": fp,
                    "old_fingerprint": old_fp,
                    "buckets": [int(b) for b in ladder],
                    "dropped_executables": int(dropped)}

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "tenants": sorted(self._tenants),
                "resident": [n for n, t in sorted(self._tenants.items())
                             if t.edition is not None
                             and t.edition.resident],
                "resident_bytes": self.resident_bytes(),
                "budget_bytes": self._budget,
                "swaps": self.swaps,
                "evictions": self.evictions,
                "rematerializations": self.rematerializations,
            }

    def summary_line(self) -> str:
        """Grep-stable exit line (``serve.py`` prints it at shutdown;
        ``make swap-smoke`` asserts on it)."""
        return (f"[tenancy] swaps={self.swaps} "
                f"evictions={self.evictions} "
                f"rematerializations={self.rematerializations} "
                f"resident={len(self.stats()['resident'])}"
                f"/{len(self._tenants)}")
