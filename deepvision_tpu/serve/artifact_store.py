"""Persistent on-disk AOT artifact store for the serving tier.

Serialized StableHLO request programs (``export.export_callable``
bytes: forward + in-graph post-processing, weights baked in as
constants) keyed exactly like ``compile_cache`` buckets — (model,
bucket, dtype, mesh, weights fingerprint) — so a fresh replica warms
its executables FROM DISK instead of re-tracing every (model, bucket)
pair: the multi-second first-burst compile storm PR 6 measured on
respawn becomes a deserialize.

Integrity follows the PR 4 checkpoint-manifest pattern
(``train/manifest.py``): every blob is recorded in ``manifest.json``
with size + SHA-256, writes stage through a tmp file unique to the
writer (pid + monotonic counter) and commit with one atomic
``os.replace``, and a blob that fails verification on read is MOVED to
``quarantine/`` (evidence, not deletion) while the caller falls back
to trace-compile. Several replicas of one fleet can therefore share a
``--store DIR`` safely: concurrent writers each stage complete bytes,
every manifest commit first folds in sibling entries it finds on disk
(so one replica's commit does not orphan another's blobs; keys this
process quarantined stay dead), and a writer killed mid-stage leaves
only its own tmp file, which readers ignore.

Concurrency shape (the JX119 contract): byte I/O never happens under
``_lock``. The in-process authority is an in-memory entries dict the
lock protects; blob bytes and manifest snapshots are staged to
writer-unique tmp files OUTSIDE the lock, and only the metadata-cheap
atomic ``os.replace`` commit (guarded by a snapshot sequence number so
an older snapshot can never overwrite a newer one) happens inside it.

The weights fingerprint in the key makes hot-swap coherent end to end:
a swapped tenant's new weights hash to a new fingerprint, so stale
artifacts exported under the old weights can never pair with them.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import os
import shutil
import threading
from pathlib import Path

__all__ = ["ArtifactStore", "mesh_desc"]

STORE_VERSION = 1

_tmp_seq = itertools.count()


def mesh_desc(mesh) -> str:
    """Canonical mesh descriptor for store keys: platform + axis
    geometry. An artifact lowered for a 4-device data axis is not
    loadable into a 2-device mesh — the descriptor keeps such blobs
    from ever being offered."""
    dev = mesh.devices.flat[0]
    axes = ",".join(f"{n}={s}" for n, s in
                    zip(mesh.axis_names, mesh.devices.shape))
    return f"{dev.platform}:{axes}"


def _entry_key(model: str, bucket: int, dtype: str, mesh: str,
               fingerprint: str) -> str:
    return f"{model}|{bucket}|{dtype}|{mesh}|{fingerprint}"


def _load_manifest_entries(path: Path) -> dict:
    try:
        data = json.loads(path.read_text())
        entries = data.get("entries")
        if not isinstance(entries, dict):
            raise ValueError("manifest has no entries mapping")
        return entries
    except (OSError, ValueError):
        return {}


class ArtifactStore:
    """Content-verified blob store under one root directory.

    Layout::

        root/manifest.json          # key -> {file, size, sha256, ...}
        root/blobs/<model>/<hash>.stablehlo
        root/quarantine/            # blobs that failed verification

    ``get`` returns the verified bytes or ``None`` (miss, or corrupt
    entry quarantined) — callers always have the trace-compile
    fallback, so the store can never make serving *less* available
    than having no store at all.
    """

    def __init__(self, root: str | Path, *, log=print):
        self.root = Path(root)
        self._log = log
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.quarantined = 0
        (self.root / "blobs").mkdir(parents=True, exist_ok=True)
        # in-process authority for entries; disk is re-consulted on a
        # miss so another replica's puts stay visible (shared --store)
        self._entries = _load_manifest_entries(self._manifest_path)
        self._snap_seq = 0       # snapshot sequence, taken under _lock
        self._committed_seq = 0  # newest snapshot committed to disk
        # tombstones: keys THIS process quarantined. The pre-commit
        # merge of sibling replicas' on-disk entries must not
        # resurrect them (a re-put with fresh bytes clears the stone).
        self._removed: set[str] = set()

    # -- manifest ---------------------------------------------------------
    @property
    def _manifest_path(self) -> Path:
        return self.root / "manifest.json"

    def _snapshot_locked(self) -> tuple[int, dict]:
        """Consistent manifest snapshot + its sequence number. Caller
        must hold ``_lock``; the snapshot is written to disk AFTER
        releasing it."""
        self._snap_seq += 1
        return self._snap_seq, {
            "version": STORE_VERSION,
            "entries": {k: dict(v) for k, v in self._entries.items()},
        }

    def _commit_manifest(self, seq: int, manifest: dict) -> None:
        """Merge, stage, commit. Replacing the whole entries dict
        last-writer-wins would orphan blobs sibling fleet replicas
        committed since our last look (their valid artifacts would
        re-trace on every respawn), so entries on the shared on-disk
        manifest that this process neither knows nor quarantined are
        folded in first — then the snapshot stages through a
        writer-unique tmp file and commits with one atomic
        ``os.replace``, guarded so a slower writer holding an OLDER
        snapshot can never clobber a newer committed one. The
        cross-process merge is best-effort (no file lock); a commit
        racing a sibling's is healed by the next merge, because the
        adopted entries persist in ``_entries``."""
        disk = _load_manifest_entries(self._manifest_path)
        with self._lock:
            if seq <= self._committed_seq:
                return  # superseded before staging; nothing written
            for k, v in disk.items():
                if k not in self._entries and k not in self._removed:
                    self._entries[k] = dict(v)
                    manifest["entries"][k] = dict(v)
            payload = json.dumps(manifest, indent=0, sort_keys=True)
        tmp = self._manifest_path.with_suffix(
            f".json.tmp.{os.getpid()}.{next(_tmp_seq)}")
        tmp.write_text(payload)
        with self._lock:
            if seq > self._committed_seq:
                os.replace(tmp, self._manifest_path)
                self._committed_seq = seq
                return
        tmp.unlink(missing_ok=True)  # superseded snapshot

    def entries(self) -> dict:
        """The current manifest entries (key -> metadata dict)."""
        with self._lock:
            return {k: dict(v) for k, v in self._entries.items()}

    # -- put / get --------------------------------------------------------
    def put(self, data: bytes, *, model: str, bucket: int, dtype: str,
            mesh: str, fingerprint: str) -> Path:
        """Persist one artifact: stage the blob through a writer-unique
        tmp file, commit with ``os.replace``, then commit the manifest
        entry the same way. Idempotent for identical content."""
        key = _entry_key(model, bucket, dtype, mesh, fingerprint)
        digest = hashlib.sha256(data).hexdigest()
        # human-greppable model dir; the rest of the key hashed into
        # the filename (mesh/dtype strings carry separators)
        blob_rel = Path("blobs") / model / (
            hashlib.sha256(key.encode()).hexdigest()[:24] + ".stablehlo")
        target = self.root / blob_rel
        target.parent.mkdir(parents=True, exist_ok=True)
        tmp = target.with_suffix(
            f".stablehlo.tmp.{os.getpid()}.{next(_tmp_seq)}")
        tmp.write_bytes(data)
        os.replace(tmp, target)
        with self._lock:
            self._entries[key] = {
                "file": str(blob_rel), "size": len(data),
                "sha256": digest, "model": model, "bucket": int(bucket),
                "dtype": dtype, "mesh": mesh, "fingerprint": fingerprint,
            }
            self._removed.discard(key)  # fresh bytes revive the key
            self.puts += 1
            seq, manifest = self._snapshot_locked()
        self._commit_manifest(seq, manifest)
        return target

    def get(self, *, model: str, bucket: int, dtype: str, mesh: str,
            fingerprint: str) -> bytes | None:
        """Verified bytes for one key, or ``None``. A manifest entry
        whose blob is missing, truncated, or hash-mismatched is
        quarantined on the way past and reported as a miss — the
        caller falls back to trace-compile."""
        key = _entry_key(model, bucket, dtype, mesh, fingerprint)
        with self._lock:
            want = self._entries.get(key)
        if want is None:
            # another replica of the fleet may have exported it since
            # our last look: re-consult the shared on-disk manifest
            disk = _load_manifest_entries(self._manifest_path).get(key)
            if disk is None:
                with self._lock:
                    self.misses += 1
                return None
            with self._lock:
                want = self._entries.setdefault(key, dict(disk))
                self._removed.discard(key)  # sibling re-published it
        path = self.root / want.get("file", "")
        try:
            data = path.read_bytes()
            if len(data) != want["size"]:
                raise ValueError(
                    f"size mismatch: {len(data)} != {want['size']}")
            if hashlib.sha256(data).hexdigest() != want["sha256"]:
                raise ValueError("checksum mismatch")
        except (OSError, ValueError, KeyError, TypeError) as e:
            self._quarantine(key, want, reason=str(e))
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
        return data

    def reject(self, *, model: str, bucket: int, dtype: str, mesh: str,
               fingerprint: str, reason: str) -> None:
        """Quarantine a verified-but-unusable entry: the bytes passed
        integrity checks but the program cannot execute on this
        backend (e.g. a custom call without serialization-compat
        guarantees). Rejecting it keeps every future warm from paying
        the same failed deserialize+compile before falling back."""
        key = _entry_key(model, bucket, dtype, mesh, fingerprint)
        with self._lock:
            entry = self._entries.get(key)
        if entry is not None:
            self._quarantine(key, entry, reason=reason)

    def _quarantine(self, key: str, entry: dict, *, reason: str) -> None:
        """Move a failing blob to ``quarantine/`` (evidence, not
        deletion) and drop its manifest entry, mirroring
        ``train/manifest.newest_verified_epoch``."""
        self._log(f"[artifact-store] {key}: {reason}; quarantining",
                  flush=True)
        qroot = self.root / "quarantine"
        qroot.mkdir(exist_ok=True)
        src = self.root / entry.get("file", "")
        if src.is_file():
            target = qroot / src.name
            n = 0
            while target.exists():
                n += 1
                target = qroot / f"{src.name}.{n}"
            shutil.move(str(src), str(target))
        with self._lock:
            self._entries.pop(key, None)
            self._removed.add(key)  # merge must not resurrect it
            self.quarantined += 1
            seq, manifest = self._snapshot_locked()
        self._commit_manifest(seq, manifest)

    # -- observability ----------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "root": str(self.root),
                "entries": len(self._entries),
                "hits": self.hits, "misses": self.misses,
                "puts": self.puts, "quarantined": self.quarantined,
            }
