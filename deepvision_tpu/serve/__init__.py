"""deepvision_tpu.serve — batched inference engine for the model zoo.

The serving runtime layer (ROADMAP north star: "serves heavy traffic"):

- ``engine``        : background dispatcher draining a bounded request
                      queue into padded, bucket-laddered micro-batches
                      over pre-compiled mesh-sharded executables, with
                      per-request futures + deadline support; runs
                      under a crash supervisor (resilience/) that fails
                      pending futures on an unexpected loop crash,
                      restarts with backoff, and degrades ``health()``
                      (``/healthz`` 503) while recovering.
- ``compile_cache`` : LRU of AOT-compiled executables keyed by
                      (model, bucket, dtype, weights fingerprint),
                      eagerly warmed so no request pays a trace.
- ``tenancy``       : multi-tenant weight residency — N models share
                      one replica's HBM behind an LRU budget (cold
                      tenants evicted to host, re-materialized on
                      demand), zero-drop weight hot-swap (new ladder
                      pre-compiled off the dispatch path, atomic
                      edition flip between batches), per-tenant
                      admission quotas + SLO classes.
- ``artifact_store``: persistent on-disk AOT store — StableHLO request
                      programs keyed like compile-cache buckets with
                      SHA-256 manifests; replicas warm from disk
                      instead of re-tracing on respawn, corrupt blobs
                      quarantine with fallback to trace.
- ``models``        : ServedModel — one restore + per-task postprocess
                      path (classify/detect/pose/gan) shared by
                      ``predict.py`` and the server; also wraps
                      StableHLO artifacts from ``export.py``.
- ``pipeline``      : device-resident DAGs of compiled stages — a
                      declarative spec (nodes/edges, validated acyclic +
                      aval-compatible before any compile) served through
                      the same queue/bucket/cache path as a model, with
                      jitted glue (top-K boxes, crop+resize, resize) and
                      ragged fan-out chunked per-stage; stage outputs
                      never touch the host until the final decode.
- ``admission``     : queue-depth backpressure, per-model limits,
                      SLO-aware deadline budgets, and
                      reject-with-retry-after shedding.
- ``telemetry``     : queue-wait / pad-overhead / device-time / e2e
                      histograms with p50/p95/p99 snapshots, plus the
                      fleet router's ``router_*`` metrics.
- ``replica``       : the fleet's unit of capacity — in-process engine
                      replicas (fast tests) and ``serve.py`` child
                      processes (production / chaos drills).
- ``router``        : SLO-aware front tier over N supervised replicas —
                      health-gated load balancing, hedged failover with
                      exactly-once results, per-model circuit breaker +
                      error budget, metric-driven autoscaling.

The CLI lives at the repo root (``serve.py``: stdin-JSONL and HTTP,
single-engine or ``--fleet N``); ``bench.py serve`` measures offered
load vs achieved throughput, ``bench.py serve --sweep`` the fleet's
latency-throughput curve + SIGKILL chaos drill.
"""

from deepvision_tpu.serve.admission import AdmissionController, ShedError
from deepvision_tpu.serve.artifact_store import ArtifactStore
from deepvision_tpu.serve.compile_cache import CompileCache
from deepvision_tpu.serve.engine import InferenceEngine
from deepvision_tpu.serve.models import (
    ServedModel,
    from_stablehlo,
    load_served,
    restore_state,
)
from deepvision_tpu.serve.pipeline import (
    ModelStage,
    Pipeline,
    PipelineError,
    PipelineSpec,
    load_pipeline_specs,
)
from deepvision_tpu.serve.replica import (
    EngineReplica,
    ProcessReplica,
    ReplicaDeadError,
)
from deepvision_tpu.serve.router import (
    AutoscaleConfig,
    Autoscaler,
    CircuitBreaker,
    CircuitConfig,
    FleetRouter,
    RouterShedError,
)
from deepvision_tpu.serve.telemetry import (
    LatencyStats,
    RouterTelemetry,
    ServeTelemetry,
)
from deepvision_tpu.serve.tenancy import TenancyManager, WeightsEdition

__all__ = [
    "AdmissionController",
    "ShedError",
    "ArtifactStore",
    "CompileCache",
    "TenancyManager",
    "WeightsEdition",
    "InferenceEngine",
    "ServedModel",
    "ModelStage",
    "Pipeline",
    "PipelineError",
    "PipelineSpec",
    "load_pipeline_specs",
    "from_stablehlo",
    "load_served",
    "restore_state",
    "EngineReplica",
    "ProcessReplica",
    "ReplicaDeadError",
    "AutoscaleConfig",
    "Autoscaler",
    "CircuitBreaker",
    "CircuitConfig",
    "FleetRouter",
    "RouterShedError",
    "LatencyStats",
    "RouterTelemetry",
    "ServeTelemetry",
]
