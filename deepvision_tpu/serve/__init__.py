"""deepvision_tpu.serve — batched inference engine for the model zoo.

The serving runtime layer (ROADMAP north star: "serves heavy traffic"):

- ``engine``        : background dispatcher draining a bounded request
                      queue into padded, bucket-laddered micro-batches
                      over pre-compiled mesh-sharded executables, with
                      per-request futures + deadline support; runs
                      under a crash supervisor (resilience/) that fails
                      pending futures on an unexpected loop crash,
                      restarts with backoff, and degrades ``health()``
                      (``/healthz`` 503) while recovering.
- ``compile_cache`` : LRU of AOT-compiled executables keyed by
                      (model, bucket, dtype), eagerly warmed so no
                      request pays a trace.
- ``models``        : ServedModel — one restore + per-task postprocess
                      path (classify/detect/pose/gan) shared by
                      ``predict.py`` and the server; also wraps
                      StableHLO artifacts from ``export.py``.
- ``admission``     : queue-depth backpressure, per-model limits, and
                      reject-with-retry-after shedding.
- ``telemetry``     : queue-wait / pad-overhead / device-time / e2e
                      histograms with p50/p95/p99 snapshots.

The CLI lives at the repo root (``serve.py``: stdin-JSONL and HTTP);
``bench.py serve`` measures offered load vs achieved throughput.
"""

from deepvision_tpu.serve.admission import AdmissionController, ShedError
from deepvision_tpu.serve.compile_cache import CompileCache
from deepvision_tpu.serve.engine import InferenceEngine
from deepvision_tpu.serve.models import (
    ServedModel,
    from_stablehlo,
    load_served,
    restore_state,
)
from deepvision_tpu.serve.telemetry import LatencyStats, ServeTelemetry

__all__ = [
    "AdmissionController",
    "ShedError",
    "CompileCache",
    "InferenceEngine",
    "ServedModel",
    "from_stablehlo",
    "load_served",
    "restore_state",
    "LatencyStats",
    "ServeTelemetry",
]
