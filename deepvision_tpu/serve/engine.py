"""Batched inference engine: queue → micro-batch → bucket → executable.

The runtime counterpart of the compile-once / shape-stable discipline
the training side already enforces (jaxlint JX105/JX110): a background
dispatcher thread drains a bounded request queue into per-model
micro-batches, pads each batch with zero rows up to a fixed bucket
ladder (default 1/4/16/64), and runs a pre-compiled, input-donated,
mesh-sharded forward per ``(model, bucket, dtype, weights
fingerprint)`` from the
:class:`~deepvision_tpu.serve.compile_cache.CompileCache` — eagerly
warmed at startup so no request ever pays a trace. This is the MLPerf
serving recipe (PAPERS.md "Scale MLPerf-0.6 models on Google TPU-v3
Pods"): sustained accelerator utilization comes from keeping a fixed
set of hot executables fed with full batches.

Guarantees (mirroring ``data/prefetch.DevicePrefetcher``'s contract
style):

- **pad isolation** — padded rows are zero inputs whose outputs are
  sliced away before postprocess; they can never leak into a result
  (per-example forwards: eval-mode BN uses running stats, so rows are
  independent).
- **bounded latency or shed** — admission control
  (``admission.AdmissionController``) rejects work with a retry-after
  hint once the queue saturates, instead of queueing into unbounded
  latency.
- **deadline honesty** — a request whose deadline passes while queued
  resolves with ``TimeoutError``, never a late (or wrong) answer.
- **clean shutdown** — ``close()`` stops and joins the dispatcher and
  fails any still-pending futures; no threads or orphaned requests
  leak.
- **crash containment** — the dispatcher runs under a supervisor
  (``_supervise``): an unexpected exception in the loop body fails
  every queued AND in-flight future with the error immediately (no
  client ever hangs until deadline expiry), is counted in telemetry
  (``dispatcher_crashes``/``dispatcher_restarts``), and the loop
  restarts with capped exponential backoff while :meth:`health`
  degrades to ``"recovering"`` (``/healthz`` serves 503) — the
  resilience/ contract: recover from routine faults, loudly.

Every request resolves a ``concurrent.futures.Future``; telemetry
(``telemetry.ServeTelemetry``) attributes each request's wall time to
queue-wait / device-time / e2e and tracks the pad overhead per batch.
"""

from __future__ import annotations

import queue
import sys
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Iterable

import numpy as np

from deepvision_tpu.obs.distributed import flight_dump
from deepvision_tpu.obs.trace import get_tracer
from deepvision_tpu.serve.admission import AdmissionController, ShedError
from deepvision_tpu.serve.compile_cache import CompileCache
from deepvision_tpu.serve.models import ServedModel
from deepvision_tpu.serve.telemetry import ServeTelemetry

__all__ = ["InferenceEngine", "ShedError"]

_WAKE = object()  # queue sentinel: wake the dispatcher without a request


class _Request:
    __slots__ = ("model", "x", "future", "t_submit", "deadline", "trace",
                 "session", "seq")

    def __init__(self, model: str, x, deadline: float | None,
                 trace: str | None = None, session: str | None = None,
                 seq: int | None = None):
        self.model = model
        self.x = x
        self.future: Future = Future()
        self.t_submit = time.perf_counter()
        self.deadline = deadline
        # distributed trace id (obs/distributed.py): stamped on the
        # replica-side queue/device/postprocess spans so one request's
        # timeline assembles across the router and replica processes
        self.trace = trace
        # stateful streams (serve/sessions.py): stream id + frame seq;
        # None for the stateless paths
        self.session = session
        self.seq = seq


class InferenceEngine:
    """Multi-model batched inference over one device mesh.

    ``models``: ServedModel instances (or a name->model dict). The
    bucket ladder applies to every model that doesn't carry its own
    (StableHLO artifacts are pinned to their exported batch). Every
    bucket must be divisible by the mesh's data-axis size — the batch
    dim is sharded over it.

    ``batch_window_s``: after the first request of a batch arrives, how
    long the dispatcher waits for the bucket to fill before running a
    partial (padded) batch. 0 trades padding for latency; saturation
    traffic fills buckets regardless via the backlog.

    ``pipelines``: built :class:`~deepvision_tpu.serve.pipeline.Pipeline`
    DAGs to serve beside the models. Each binds to the engine's shared
    compile cache + mesh and then rides the SAME queue/bucket/admission
    path as a model — ``submit(x, model=<pipeline name>)`` just works,
    and ``warm()`` compiles every stage of every pipeline end-to-end.

    ``freeze_cache``: freeze the compile cache after warmup — any
    request-time miss raises instead of tracing, proving no request
    (pipeline or plain) can ever pay a hidden compile.

    Multi-tenancy (``serve/tenancy.py``): ``store`` (an
    ``ArtifactStore`` or a directory path) warms executables from
    disk and exports trace-compiled ones back; ``residency_bytes``
    caps resident weight bytes with LRU eviction to host;
    ``tenant_quota`` / ``slo_class`` thread per-tenant admission
    isolation into the :class:`AdmissionController`. :meth:`hot_swap`
    replaces one tenant's weights under live load with zero drops.
    """

    def __init__(
        self,
        models: Iterable[ServedModel] | dict[str, ServedModel],
        *,
        mesh=None,
        buckets: tuple[int, ...] = (1, 4, 16, 64),
        max_queue: int = 256,
        per_model_limit: int | None = None,
        batch_window_s: float = 0.0,
        warmup: bool = True,
        cache_entries: int = 64,
        telemetry: ServeTelemetry | None = None,
        fault_injector=None,
        restart_backoff_s: float = 0.05,
        restart_backoff_max_s: float = 5.0,
        pipelines: Iterable = (),
        freeze_cache: bool = False,
        store=None,
        residency_bytes: int | None = None,
        tenant_quota: dict[str, int] | None = None,
        slo_class: dict[str, str] | None = None,
    ):
        if isinstance(models, dict):
            self._models = dict(models)
        else:
            self._models = {m.name: m for m in models}
        if not self._models:
            raise ValueError("engine needs at least one ServedModel")
        if not buckets or list(buckets) != sorted(set(buckets)):
            raise ValueError(f"bucket ladder must be sorted unique, "
                             f"got {buckets}")
        if mesh is None:
            from deepvision_tpu.core.mesh import create_mesh

            mesh = create_mesh(1, 1)  # single-device default: serving a
            # host; pass an explicit mesh to shard batches over chips
        self._mesh = mesh
        self.buckets = tuple(buckets)
        self._cache = CompileCache(max_entries=cache_entries)
        for p in pipelines:
            if p.name in self._models:
                raise ValueError(
                    f"pipeline {p.name!r} collides with a served model")
            # bind before _check_ladders: divisibility is checked for
            # every STAGE ladder, not just the pipeline's entry ladder
            p.bind(self._cache, self._mesh, self.buckets)
            self._models[p.name] = p
        self._check_ladders()
        self.telemetry = telemetry if telemetry is not None \
            else ServeTelemetry()
        self._admission = AdmissionController(
            max_queue=max_queue, per_model_limit=per_model_limit,
            tenant_quota=tenant_quota, slo_class=slo_class)
        self._window = batch_window_s
        self._poll_s = 0.05
        self._q: queue.Queue = queue.Queue()
        self._stop = threading.Event()
        self._paused = threading.Event()
        # dispatcher supervision state: per-model backlog + the batch
        # currently in the loop's hands live on the INSTANCE so a crash
        # handler can fail every one of their futures (a local would
        # strand them un-resolvable — clients hang to deadline expiry)
        self._pending: dict[str, list[_Request]] = {
            name: [] for name in self._models}
        self._in_flight: list[_Request] = []
        self._recovering = threading.Event()
        # guards _recover_until: written by the supervisor thread,
        # read by health() probes from any thread (jaxlint JX118 — the
        # Event alone orders the write but a linter, and the next
        # maintainer, should not have to prove publication order)
        self._health_lock = threading.Lock()
        self._recover_until = 0.0  # monotonic end of the backoff window
        self._injector = fault_injector
        self._restart_backoff_s = restart_backoff_s
        self._restart_backoff_max_s = restart_backoff_max_s
        self._backoff_reset_s = 5.0  # healthy-for-this-long resets backoff
        self.warmup_s = 0.0
        if store is not None and not hasattr(store, "get"):
            from deepvision_tpu.serve.artifact_store import ArtifactStore

            store = ArtifactStore(store, log=self._log)
        self._store = store
        # (model, bucket, dtype, fp) keys whose executables came off
        # disk instead of a trace — the respawn-without-compile-storm
        # evidence ``stats()`` reports and bench pins
        self._from_store: set = set()
        from deepvision_tpu.serve.tenancy import TenancyManager

        self._tenancy = TenancyManager(
            self._mesh, budget_bytes=residency_bytes, log=self._log)
        self._adopt_tenants()
        if warmup:
            self.warm()
            if freeze_cache:
                # warmed end-to-end (pipelines included): any later
                # miss is a hidden request-time compile — fail loudly
                self._cache.freeze()
        self._thread = threading.Thread(
            target=self._supervise, name="serve-dispatch", daemon=True
        )
        self._thread.start()

    # -- setup -----------------------------------------------------------
    def _check_ladders(self) -> None:
        from deepvision_tpu.core.mesh import axis_size

        n_data = axis_size(self._mesh)
        for m in self._models.values():
            for b in self.ladder(m):
                if b % n_data:
                    raise ValueError(
                        f"bucket {b} for model {m.name!r} is not "
                        f"divisible by the mesh data axis ({n_data}); "
                        "batches are sharded over it")

    @staticmethod
    def _log(*args, **kw) -> None:
        # tenancy/store chatter goes to stderr: stdout is the JSONL
        # protocol stream when serve.py hosts this engine
        print(*args, file=sys.stderr, **kw)

    def _adopt_tenants(self) -> None:
        """Register every weight-carrying model (pipeline/stateful
        STAGE models included — shared objects with the plain serving
        path) with the tenancy manager: one fingerprint + one
        replicated device placement + a weights edition each, so
        per-batch calls never re-place (or worse, re-transfer) params
        and eviction/hot-swap have their seam."""
        for m in self._models.values():
            if getattr(m, "is_pipeline", False) \
                    or getattr(m, "is_stateful", False):
                # a pipeline's own variables are None; its STAGE models
                # carry the weights
                for sm in m.stage_models().values():
                    self._tenancy.adopt(sm)
            else:
                self._tenancy.adopt(m)

    def _tenant_names(self, served) -> list[str]:
        if getattr(served, "is_pipeline", False) \
                or getattr(served, "is_stateful", False):
            return list(served.stage_models())
        return [served.name]

    def _model_key(self, m, bucket: int) -> tuple:
        """Compile-cache key: ``(model, bucket, dtype, weights
        fingerprint)``. The fingerprint pins an executable to the
        weights generation it was compiled against — after a hot-swap
        the key changes, so a stale executable can never silently pair
        with new weights. Pipelines/stateful wrappers key their front
        door ``"static"``: their weights live in the per-stage cache
        entries, which carry the stage fingerprints."""
        fp = getattr(m, "weights_fingerprint", None)
        return (m.name, bucket, m.dtype_str,
                fp() if fp is not None else "static")

    def ladder(self, model: ServedModel) -> tuple[int, ...]:
        return model.buckets if model.buckets else self.buckets

    def warm(self) -> None:
        """Eagerly compile every (model, bucket) executable so no
        request ever pays a trace; time recorded in ``warmup_s``.

        Precompiled (StableHLO-artifact) runners additionally execute
        once on a zero batch fed through the EXACT request path
        (``device_put`` with the batch sharding): ``jax.export``
        serializes StableHLO, not machine code, so the deserialized
        callable compiles for the local backend on first call — and it
        specializes on the input's placement, so a numpy-fed warmup
        would leave the device-array-fed request path still cold.
        Without this, the engine's "no request pays a compile" contract
        silently broke for artifacts (measured as a multi-second stall
        of the first request burst on every fresh replica).

        With an artifact store attached, every storeable (model,
        bucket) first tries the disk: a verified StableHLO blob under
        this mesh + weights fingerprint deserializes into the cache
        (``install``, no miss counted) instead of paying the trace —
        the respawn path PR 6 measured stops re-compiling. Misses
        trace-compile as before and are exported back into the store,
        so the first replica of a fleet populates it for the rest."""
        import jax

        from deepvision_tpu.core.mesh import data_sharding

        t0 = time.perf_counter()
        for m in self._models.values():
            for bucket in self.ladder(m):
                key = self._model_key(m, bucket)
                runner = None
                if self._store is not None and self._storeable(m):
                    runner = self._load_store_runner(m, bucket)
                    if runner is not None:
                        self._cache.install(key, runner)
                        self._from_store.add(key)
                from_store = runner is not None
                if runner is None:
                    runner = self._cache.get_or_build(
                        key,
                        lambda m=m, bucket=bucket: m.compile_for(
                            bucket, self._mesh),
                    )
                    if self._store is not None and self._storeable(m):
                        self._save_store_entry(m, bucket)
                if from_store or m.precompiled is not None \
                        or getattr(m, "is_pipeline", False) \
                        or getattr(m, "is_stateful", False):
                    # pipelines zero-execute too: their runners thread
                    # eager device ops (chunk slice/pad/concat, dict
                    # re-packing) between stage executables, and any
                    # StableHLO artifact — pre-exported or store-loaded
                    # — backend-compiles on first call AND specializes
                    # on input placement, so the zero batch feeds
                    # through the exact request path
                    x = np.zeros((bucket, *m.input_shape), m.input_dtype)
                    xd = jax.device_put(
                        x, data_sharding(self._mesh, x.ndim))
                    try:
                        jax.device_get(runner(xd))
                    except Exception as e:
                        if not from_store:
                            raise
                        # the blob deserialized but cannot EXECUTE on
                        # this backend (e.g. a custom call without
                        # serialization-compat guarantees): reject it
                        # so future respawns skip it, and trace-compile
                        # — the store must never make warmup fail, only
                        # faster. No re-export: the same program just
                        # proved un-runnable from serialized form here.
                        self._log(
                            f"[artifact-store] {m.name}@{bucket}: "
                            f"stored program failed to execute ({e}); "
                            "rejecting + re-tracing")
                        self._reject_store_entry(m, bucket,
                                                 reason=str(e))
                        self._cache.drop_where(
                            lambda k, key=key: k == key)
                        self._from_store.discard(key)
                        self._cache.get_or_build(
                            key,
                            lambda m=m, bucket=bucket: m.compile_for(
                                bucket, self._mesh),
                        )
        if self._store is not None:
            # a tenant whose ENTIRE ladder deserialized from the store
            # serves programs with the weights baked in as constants:
            # nothing reads its edition at call time, so the adopted
            # device copy is released to host and the tenant leaves
            # the residency budget's LRU (an eviction could not free
            # baked constants anyway). Partially store-warmed models
            # keep their edition resident — their trace-compiled
            # buckets read it. A later hot-swap compiles edition-
            # backed runners and re-enters residency management.
            for m in self._models.values():
                if not self._storeable(m):
                    continue
                keys = [self._model_key(m, b) for b in self.ladder(m)]
                if all(k in self._from_store for k in keys):
                    self._tenancy.release_to_baked(m, len(keys))
        self.warmup_s = round(time.perf_counter() - t0, 3)

    def _storeable(self, m) -> bool:
        """Models whose request program the artifact store can carry:
        plain weight-backed forwards. Pipelines re-assemble from their
        (storeable) stages' trace path, pre-exported artifacts already
        ARE serialized programs, and stateful wrappers hold live
        device state no AOT blob can bake in."""
        return (not getattr(m, "is_pipeline", False)
                and not getattr(m, "is_stateful", False)
                and getattr(m, "precompiled", None) is None
                and getattr(m, "variables", None) is not None)

    def _load_store_runner(self, m, bucket: int):
        """Verified store bytes -> runner, or None (miss / corrupt —
        the store quarantined it — / undeserializable): the caller
        falls back to trace-compile, so the store never makes warmup
        *fail*, only faster."""
        from deepvision_tpu.export import deserialize_exported
        from deepvision_tpu.serve.artifact_store import mesh_desc

        data = self._store.get(
            model=m.name, bucket=bucket, dtype=m.dtype_str,
            mesh=mesh_desc(self._mesh),
            fingerprint=m.weights_fingerprint())
        if data is None:
            return None
        try:
            return deserialize_exported(data)
        except Exception as e:
            self._log(f"[artifact-store] {m.name}@{bucket}: "
                      f"deserialize failed ({e}); re-tracing")
            return None

    def _save_store_entry(self, m, bucket: int) -> None:
        """Best-effort export into the store — a full disk must never
        take serving down with it."""
        from deepvision_tpu.serve.artifact_store import mesh_desc

        try:
            self._store.put(
                m.export_bytes(bucket), model=m.name, bucket=bucket,
                dtype=m.dtype_str, mesh=mesh_desc(self._mesh),
                fingerprint=m.weights_fingerprint())
        except Exception as e:
            self._log(f"[artifact-store] export {m.name}@{bucket} "
                      f"failed: {e}")

    def _reject_store_entry(self, m, bucket: int, *,
                            reason: str) -> None:
        """Quarantine a store entry that deserialized but could not
        execute here — best-effort, like every store write."""
        from deepvision_tpu.serve.artifact_store import mesh_desc

        try:
            self._store.reject(
                model=m.name, bucket=bucket, dtype=m.dtype_str,
                mesh=mesh_desc(self._mesh),
                fingerprint=m.weights_fingerprint(), reason=reason)
        except Exception as e:
            self._log(f"[artifact-store] reject {m.name}@{bucket} "
                      f"failed: {e}")

    # -- tenancy ---------------------------------------------------------
    def hot_swap(self, name: str, variables=None, *,
                 workdir: str | None = None,
                 perturb: float | None = None) -> dict:
        """Zero-drop weight hot-swap for one tenant. Runs on the
        CALLER's thread: the new weights are staged and the whole
        bucket ladder pre-compiled off the dispatch path, then the
        tenant's weights edition flips atomically between batches —
        requests already dispatched against the pre-swap executables
        drain on the pre-swap weights (their runners keep their
        compile-time edition), and nothing is ever dropped.

        Exactly one source: ``variables`` (a ready pytree),
        ``workdir`` (restore the latest checkpoint), or ``perturb``
        (current weights + a float constant — the smoke-drill path:
        guarantees a new fingerprint without a second checkpoint).

        Pipelines that use this model as a STAGE keep serving the
        weights they warmed with (their DAG runners captured the old
        edition at compile time) until re-registered — the front-door
        path for ``name`` swaps; DAGs are deliberately immutable."""
        served = self._models.get(name)
        if served is None:
            raise ValueError(f"unknown model {name!r}; serving "
                             f"{sorted(self._models)}")
        if getattr(served, "is_pipeline", False) \
                or getattr(served, "is_stateful", False):
            kind = ("pipeline" if getattr(served, "is_pipeline", False)
                    else "stateful wrapper")
            raise ValueError(
                f"{name!r} is a {kind}; hot-swap targets its stage "
                "models' front doors")
        if served.variables is None:
            raise ValueError(
                f"{name!r} is a StableHLO artifact (weights baked into "
                "the program); register a new artifact instead")
        if sum(v is not None for v in (variables, workdir, perturb)) != 1:
            raise ValueError(
                "pass exactly one of variables=, workdir=, perturb=")
        if workdir is not None:
            from deepvision_tpu.serve.models import (
                _state_variables,
                model_geometry,
                restore_state,
            )

            size, ch = model_geometry(name)
            state = restore_state(
                name, workdir, np.zeros((1, size, size, ch), np.float32))
            variables = _state_variables(state)
        if perturb is not None:
            import jax

            def _nudge(a):
                a = np.asarray(a)
                if np.issubdtype(a.dtype, np.floating):
                    return (a + perturb).astype(a.dtype)
                return a

            variables = jax.tree_util.tree_map(
                _nudge, served.edition.variables)
        result = self._tenancy.swap(
            served, variables, ladder=self.ladder(served),
            mesh=self._mesh, cache=self._cache,
            key_fn=self._model_key)
        if result.get("unchanged"):
            # same-fingerprint swap: the live ladder already pairs
            # with these exact bytes — nothing installed, nothing
            # dropped, nothing to re-export
            return result
        if self._from_store:
            # the swap dropped any store-warmed (baked-weights)
            # runners for this tenant; stats must stop claiming them
            self._from_store = {
                k for k in self._from_store if k[0] != name}
        if self._store is not None and self._storeable(served):
            # keep the store current: a replica respawned after the
            # swap warms the NEW fingerprint from disk
            for bucket in self.ladder(served):
                self._save_store_entry(served, bucket)
        return result

    def _bucket_runner(self, served, bucket: int):
        """The cached executable for (model, bucket) with swap
        consistency: if a hot-swap flips the weights edition between
        the key read and the cache lookup, retry — the runner an
        executable key names must always pair with the weights
        generation in that key (satellite-bugfix contract)."""
        while True:
            key = self._model_key(served, bucket)
            runner = self._cache.get_or_build(
                key, lambda: served.compile_for(bucket, self._mesh))
            if key == self._model_key(served, bucket):
                return runner

    @property
    def tenancy(self):
        """The engine's :class:`~deepvision_tpu.serve.tenancy.
        TenancyManager` (always present; budget-less by default) —
        ``serve.py`` prints its grep-stable summary line at exit."""
        return self._tenancy

    # -- client surface --------------------------------------------------
    def submit(self, x, model: str | None = None, *,
               timeout_s: float | None = None,
               trace: str | None = None,
               session: str | None = None,
               seq: int | None = None) -> Future:
        """Enqueue one example (no batch dim) for ``model``; returns a
        Future resolving to the task's result dict. Raises
        :class:`ShedError` immediately when admission rejects, and
        ``ValueError`` on shape/model mismatch (fail fast, not in the
        dispatcher). ``trace`` is the request's distributed trace id
        (propagated from the router over ``X-DVTPU-Trace``): the
        per-request queue/device/postprocess spans carry it.

        Stateful models (``serve/sessions.py``) additionally require
        ``session`` (stream id) + ``seq`` (frame number): the session's
        device state threads through this same admission/deadline path,
        and a NEW session is shed here when the store is at capacity."""
        if model is None:
            if len(self._models) != 1:
                raise ValueError(
                    f"engine hosts {sorted(self._models)}; pass model=")
            (model,) = self._models  # the single-model host default
        served = self._models.get(model)
        if served is None:
            raise ValueError(f"unknown model {model!r}; serving "
                             f"{sorted(self._models)}")
        if self._stop.is_set():
            raise RuntimeError("engine is closed")
        x = np.asarray(x, dtype=served.input_dtype)
        if x.shape != served.input_shape:
            raise ValueError(
                f"{model!r} expects input shape {served.input_shape}, "
                f"got {x.shape}")
        if getattr(served, "is_stateful", False):
            if session is None or seq is None:
                raise ValueError(
                    f"stateful model {model!r} requires session= and "
                    "seq= on submit")
            seq = int(seq)
            if seq < 0:
                raise ValueError(f"seq must be >= 0, got {seq}")
            try:
                # capacity sheds NEW sessions at the door; existing
                # streams keep their state (never a silent reset)
                served.store.admit(session)
            except ShedError:
                self.telemetry.record_shed()
                raise
        elif session is not None:
            raise ValueError(
                f"model {model!r} is stateless; session=/seq= is only "
                "valid for stateful models")
        try:
            self._admission.admit(model)
        except ShedError:
            self.telemetry.record_shed()
            raise
        self.telemetry.record_submit()
        req = _Request(
            model, x,
            deadline=(time.perf_counter() + timeout_s
                      if timeout_s is not None else None),
            trace=trace, session=session, seq=seq)
        self._q.put(req)
        if self._stop.is_set():
            # raced close(): the dispatcher's exit drain may already
            # have passed — make sure this future resolves either way.
            # Releaser = whoever resolves the future, exactly once
            # (same rule as _resolve_dropped), so the slot is never
            # double-released when both sides race.
            try:
                req.future.set_exception(RuntimeError("engine closed"))
            except InvalidStateError:
                pass  # dispatcher's drain resolved (and released)
            else:
                self._admission.release(model)
        return req.future

    def _session_stores(self) -> dict:
        """name -> SessionStore for every stateful model."""
        return {name: m.store for name, m in self._models.items()
                if getattr(m, "is_stateful", False)}

    def stats(self) -> dict:
        """JSON-able state for ``/stats`` and the bench report."""
        out = {
            "models": sorted(self._models),
            "pipelines": {
                name: m.requests_served
                for name, m in sorted(self._models.items())
                if getattr(m, "is_pipeline", False)},
            "buckets": list(self.buckets),
            "warmup_s": self.warmup_s,
            "health": self.health(),
            "queue": self._admission.stats(),
            "cache": self._cache.stats(),
            "tenancy": self._tenancy.stats(),
            "warmed_from_store": sorted(
                f"{k[0]}@{k[1]}" for k in self._from_store),
            "telemetry": self.telemetry.snapshot(),
        }
        if self._store is not None:
            out["artifact_store"] = self._store.stats()
        stores = self._session_stores()
        if stores:
            out["sessions"] = {name: s.stats()
                               for name, s in sorted(stores.items())}
        return out

    def health(self) -> dict:
        """Liveness for ``/healthz``: ``"recovering"`` while the
        supervisor sits in a post-crash backoff window (the CLI serves
        503 then — load balancers should drain, not route), ``"ok"``
        otherwise. Crash/restart counts ride along so a probe can tell
        self-healed from never-faulted."""
        recovering = self._recovering.is_set()
        out = {
            "status": "recovering" if recovering else "ok",
            "dispatcher_crashes": self.telemetry.dispatcher_crashes,
            "dispatcher_restarts": self.telemetry.dispatcher_restarts,
        }
        if recovering:
            # when to re-probe: the rest of the backoff window — the
            # /healthz 503 carries it as Retry-After so load balancers
            # re-probe on schedule instead of hammering or forgetting
            with self._health_lock:
                until = self._recover_until
            out["retry_after_s"] = round(
                max(0.05, until - time.monotonic()), 3)
        stores = self._session_stores()
        if stores:
            # stateful-serving liveness: live streams, device bytes
            # pinned by their state, and the worst-case snapshot age
            # (how much replay a crash right now would need)
            agg = [s.stats() for s in stores.values()]
            ages = [a["snapshot_age_s"] for a in agg
                    if a["snapshot_age_s"] is not None]
            out["sessions"] = {
                "live": sum(a["live"] for a in agg),
                "pinned_bytes": sum(a["pinned_bytes"] for a in agg),
                "snapshot_age_s": max(ages) if ages else None,
            }
        return out

    # pause/resume: used by drains and tests that need deterministic
    # queue buildup (backpressure, deadline expiry) without sleeping on
    # a compile race
    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()
        self._q.put(_WAKE)

    # -- dispatcher ------------------------------------------------------
    def _supervise(self) -> None:
        """Run the dispatch loop under crash supervision: an unexpected
        exception (anything ``_run_batch``'s per-batch containment did
        not absorb) fails every queued and in-flight future with the
        error — immediately, not at deadline expiry — then the loop
        restarts after a capped exponential backoff. ``health()``
        reports ``"recovering"`` for the backoff window. Backoff resets
        once a loop incarnation survives ``_backoff_reset_s``, so an
        engine that crashes once a day never escalates to max delay."""
        backoff = self._restart_backoff_s
        while True:
            t0 = time.monotonic()
            try:
                self._dispatch_loop()
                return  # clean close(): loop drained and exited
            except BaseException as e:
                self.telemetry.record_dispatcher_crash()
                # black box first: the flight recorder's ring holds the
                # spans/metric deltas leading up to exactly this moment
                flight_dump("dispatcher_crash")
                n = self._fail_all_pending(RuntimeError(
                    f"dispatcher crashed: {type(e).__name__}: {e}"))
                print(f"[serve-supervisor] dispatcher crashed "
                      f"({type(e).__name__}: {e}); failed {n} pending "
                      f"request(s); restarting in {backoff:.2f}s",
                      file=sys.stderr, flush=True)
                if self._stop.is_set():
                    # closing: drain anything submitted since the crash
                    self._fail_all_pending(RuntimeError("engine closed"))
                    return
                if time.monotonic() - t0 > self._backoff_reset_s:
                    backoff = self._restart_backoff_s
                with self._health_lock:
                    self._recover_until = time.monotonic() + backoff
                self._recovering.set()
                self._stop.wait(backoff)  # close() wakes this instantly
                self._recovering.clear()
                if self._stop.is_set():
                    self._fail_all_pending(RuntimeError("engine closed"))
                    return
                backoff = min(backoff * 2, self._restart_backoff_max_s)
                self.telemetry.record_dispatcher_restart()

    def _dispatch_loop(self) -> None:
        pending = self._pending
        rr = list(self._models)  # round-robin cursor over models
        while not self._stop.is_set():
            if self._paused.is_set():
                # stop-responsive pause poll (jaxlint JX113): a bare
                # time.sleep here would hold close() hostage to the
                # poll tick instead of waking on the stop event
                self._stop.wait(0.002)
                continue
            self._drain_inbound(
                pending, block=not any(pending.values()))
            if self._stop.is_set() or self._paused.is_set():
                continue
            name = self._next_model(pending, rr)
            if name is None:
                continue
            served = self._models[name]
            ladder_max = max(self.ladder(served))
            self._fill_window(pending, name, ladder_max)
            reqs = pending[name][:ladder_max]
            del pending[name][:ladder_max]
            if getattr(served, "is_stateful", False):
                # one frame per session per batch: the compiled update
                # reads each row's PRE-batch slate, so two frames of one
                # stream in a batch would both read stale state. Later
                # frames return to the FRONT of the backlog in arrival
                # order — per-stream FIFO holds across the deferral.
                seen: set[str] = set()
                keep: list[_Request] = []
                defer: list[_Request] = []
                for r in reqs:
                    if r.session in seen:
                        defer.append(r)
                    else:
                        seen.add(r.session)
                        keep.append(r)
                if defer:
                    pending[name][:0] = defer
                    reqs = keep
            # visible to the crash handler from the moment they leave
            # the backlog: a crash anywhere past the slice (deadline
            # expiry included) must fail THESE futures too, or their
            # clients hang and their admission slots leak
            self._in_flight = reqs
            live = self._expire(reqs)
            if live:
                self._in_flight = live
                if self._injector is not None:
                    self._injector.check_dispatch()  # chaos site
                self._run_batch(served, live)
            self._in_flight = []
        # drain: fail anything still queued/pending so no caller blocks
        # forever on a future the dispatcher will never resolve
        self._drain_inbound(pending, block=False)
        for reqs in pending.values():
            for r in reqs:
                self._resolve_dropped(r)
            reqs.clear()

    def _fail_all_pending(self, exc: BaseException) -> int:
        """Resolve every queued + in-flight future with ``exc`` (counted
        as failures, admission slots released); -> how many."""
        n = 0
        self._drain_inbound(self._pending, block=False)
        for r in self._in_flight:
            n += self._fail_request(r, exc)
        self._in_flight = []
        for reqs in self._pending.values():
            for r in reqs:
                n += self._fail_request(r, exc)
            reqs.clear()
        return n

    def _fail_request(self, r: _Request, exc: BaseException) -> int:
        # releaser = whoever resolves the future, exactly once (the
        # raced-close branch of submit() follows the same rule)
        try:
            r.future.set_exception(exc)
        except InvalidStateError:
            return 0  # already resolved (and released) elsewhere
        self.telemetry.record_failure()
        self._admission.release(r.model)
        return 1

    def _drain_inbound(self, pending, block: bool) -> None:
        try:
            item = (self._q.get(timeout=self._poll_s) if block
                    else self._q.get_nowait())
        except queue.Empty:
            return
        while True:
            if item is not _WAKE:
                pending[item.model].append(item)
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return

    @staticmethod
    def _next_model(pending, rr: list[str]) -> str | None:
        for _ in range(len(rr)):
            name = rr.pop(0)
            rr.append(name)
            if pending[name]:
                return name
        return None

    def _fill_window(self, pending, name: str, ladder_max: int) -> None:
        """Give the queue up to ``batch_window_s`` (from the oldest
        pending request) to fill the largest bucket before running a
        padded partial batch."""
        if self._window <= 0:
            return
        until = pending[name][0].t_submit + self._window
        while len(pending[name]) < ladder_max \
                and not self._stop.is_set():
            remaining = until - time.perf_counter()
            if remaining <= 0:
                return
            try:
                item = self._q.get(timeout=remaining)
            except queue.Empty:
                return
            if item is not _WAKE:
                pending[item.model].append(item)

    def _expire(self, reqs: list[_Request]) -> list[_Request]:
        now = time.perf_counter()
        live = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                try:
                    r.future.set_exception(TimeoutError(
                        f"deadline expired after "
                        f"{now - r.t_submit:.3f}s in queue"))
                except InvalidStateError:
                    continue  # raced close() resolved (and released) it
                self.telemetry.record_timeout()
                self._admission.release(r.model)
            else:
                live.append(r)
        return live

    def _bucket_for(self, served: ServedModel, n: int) -> int:
        for b in self.ladder(served):
            if b >= n:
                return b
        return max(self.ladder(served))

    def _run_batch(self, served: ServedModel, reqs: list[_Request]) -> None:
        import jax

        from deepvision_tpu.core.mesh import data_sharding

        if getattr(served, "is_stateful", False):
            self._run_stateful_batch(served, reqs)
            return
        t_dispatch = time.perf_counter()
        n = len(reqs)
        bucket = self._bucket_for(served, n)
        x = np.zeros((bucket, *served.input_shape), served.input_dtype)
        for i, r in enumerate(reqs):
            x[i] = r.x
        try:
            # residency first: a cold tenant's weights come back to the
            # device (and LRU victims leave) BEFORE the executable runs
            for tn in self._tenant_names(served):
                self._tenancy.ensure_resident(tn)
            runner = self._bucket_runner(served, bucket)
            xd = jax.device_put(x, data_sharding(self._mesh, x.ndim))
            t0 = time.perf_counter()
            host = jax.device_get(runner(xd))
            t_dev = time.perf_counter() - t0
        except Exception as e:  # device/compile failure: fail the batch
            for r in reqs:
                r.future.set_exception(e)
                self.telemetry.record_failure()
                self._admission.release(r.model)
            return
        self.telemetry.record_batch(bucket=bucket, rows=n, device_s=t_dev)
        self._admission.observe_batch(t_dev, n)
        is_pipeline = getattr(served, "is_pipeline", False)
        expired: set[int] = set()
        if is_pipeline:
            served.record_served(n)
            # deadline honesty holds mid-DAG too: a multi-stage run can
            # outlive a request's deadline after queue-time expiry
            # passed it — resolve TimeoutError (exactly once; the
            # try/except is the same releaser rule as _expire), never a
            # late answer
            t_now = time.perf_counter()
            for r in reqs:
                if r.deadline is not None and t_now > r.deadline:
                    try:
                        r.future.set_exception(TimeoutError(
                            f"deadline expired mid-pipeline after "
                            f"{t_now - r.t_submit:.3f}s"))
                    except InvalidStateError:
                        continue
                    self.telemetry.record_timeout()
                    self._admission.release(r.model)
                    expired.add(id(r))
        tracer = get_tracer()
        if tracer.active:
            # retroactive spans from the stamps this loop already takes
            # (obs/trace.py record_span — same perf_counter clock): the
            # replica half of the distributed request timeline. The
            # device span already measured completed compute —
            # device_get above drained the dispatch, the JX112/JX117
            # contract
            traces = [r.trace for r in reqs if r.trace]
            tracer.record_span(
                "device", t0, t0 + t_dev, cat="serve",
                args={"model": served.name, "bucket": bucket, "rows": n,
                      **({"traces": traces} if traces else {})})
            if is_pipeline:
                # one span per DAG stage, stamped with every request
                # trace id in the batch: the trace ids flow router ->
                # replica_queue -> device -> stage:<node> -> postprocess
                # in a single Perfetto timeline (trace_merge
                # --assert-flow proves the crossing)
                for stage_name, s0, s1 in served.take_stage_stamps():
                    tracer.record_span(
                        f"stage:{stage_name}", s0, s1, cat="serve",
                        args={"pipeline": served.name,
                              "stage": stage_name,
                              **({"traces": traces} if traces else {})})
            for r in reqs:
                if r.trace:
                    tracer.record_span(
                        "replica_queue", r.t_submit, t_dispatch,
                        cat="serve",
                        args={"trace": r.trace, "model": served.name})
        now = time.perf_counter()
        for i, r in enumerate(reqs):
            if id(r) in expired:
                continue  # resolved TimeoutError above, slot released
            t_pp = time.perf_counter()
            try:
                result = served.postprocess(host, i)
            except Exception as e:
                r.future.set_exception(e)
                self.telemetry.record_failure()
            else:
                r.future.set_result(result)
                self.telemetry.record_request(
                    queue_wait_s=t_dispatch - r.t_submit,
                    e2e_s=now - r.t_submit)
            if r.trace and tracer.active:
                tracer.record_span(
                    "postprocess", t_pp, time.perf_counter(),
                    cat="serve", args={"trace": r.trace})
            self._admission.release(r.model)

    def _run_stateful_batch(self, served, reqs: list[_Request]) -> None:
        """Dispatch one batch of a stateful model (TrackingPipeline):
        disposition each frame through the SessionStore, answer
        duplicates idempotently, then run the detect and interpolate
        sub-batches as separate compiled programs. State stays on
        device — ONE ``device_get`` of the batch OUTPUT per sub-batch,
        never a per-frame round trip on state leaves (the JX128
        contract); the only state fetch is the store's on-cadence
        snapshot inside ``commit``."""
        store = served.store
        t_dispatch = time.perf_counter()
        frames = [(r, store.begin_frame(r.session, r.seq,
                                        served.detect_every))
                  for r in reqs]
        dup = [(r, f) for r, f in frames if f.action == "duplicate"]
        detect = [(r, f) for r, f in frames
                  if f.action == "apply" and f.run_detect]
        interp = [(r, f) for r, f in frames
                  if f.action == "apply" and not f.run_detect]
        now = time.perf_counter()
        for r, _f in dup:
            # idempotent replay/retry answer: seq already applied, no
            # recompute, no state touched (same exactly-once releaser
            # rule as everywhere else)
            try:
                r.future.set_result({"session": r.session, "seq": r.seq,
                                     "replayed": True,
                                     "state_reset": False})
            except InvalidStateError:
                continue
            self.telemetry.record_request(
                queue_wait_s=t_dispatch - r.t_submit,
                e2e_s=now - r.t_submit)
            self._admission.release(r.model)
        for group, mode in ((detect, "detect"), (interp, "interp")):
            if group:
                self._run_stateful_group(
                    served, store, group, mode, t_dispatch)

    def _run_stateful_group(self, served, store, group,
                            mode: str, t_dispatch: float) -> None:
        import jax
        import jax.numpy as jnp

        from deepvision_tpu.core.mesh import data_sharding

        n = len(group)
        bucket = self._bucket_for(served, n)
        x = np.zeros((bucket, *served.input_shape), served.input_dtype)
        for i, (r, _f) in enumerate(group):
            x[i] = r.x
        try:
            for tn in self._tenant_names(served):
                self._tenancy.ensure_resident(tn)
            runner = self._bucket_runner(served, bucket)
            zero = runner.zero_slates()
            # stack per-session device rows (zero rows for fresh/reset
            # streams and padding) into the batched slate pytree
            slates = {
                k: jnp.stack([
                    group[i][1].entry.state[k]
                    if i < n and group[i][1].entry.state is not None
                    else zero[k][i]
                    for i in range(bucket)])
                for k in zero}
            xd = jax.device_put(x, data_sharding(self._mesh, x.ndim))
            t0 = time.perf_counter()
            if mode == "detect":
                new_slates, out = runner.update(slates, runner.detect(xd))
            else:
                new_slates, out = runner.advance(slates)
            host = jax.device_get(out)  # ONE host sync for the batch
            t_dev = time.perf_counter() - t0
        except Exception as e:  # device/compile failure: fail the group
            for r, _f in group:
                self._fail_request(r, e)
            return
        self.telemetry.record_batch(bucket=bucket, rows=n, device_s=t_dev)
        self._admission.observe_batch(t_dev, n)
        tracer = get_tracer()
        if tracer.active:
            traces = [r.trace for r, _f in group if r.trace]
            sessions = [r.session for r, _f in group]
            tracer.record_span(
                "device", t0, t0 + t_dev, cat="serve",
                args={"model": served.name, "bucket": bucket, "rows": n,
                      "mode": mode, "sessions": sessions,
                      **({"traces": traces} if traces else {})})
        now = time.perf_counter()
        for i, (r, f) in enumerate(group):
            # commit state FIRST: the stream's lineage advances even if
            # this answer expired — the client's retry then dedupes as
            # an idempotent duplicate instead of forking the stream
            row = {k: new_slates[k][i] for k in new_slates}
            store.commit(r.session, r.seq, row)
            if r.deadline is not None and now > r.deadline:
                # deadline honesty mid-batch (same rule as pipelines):
                # never a late answer
                try:
                    r.future.set_exception(TimeoutError(
                        f"deadline expired mid-batch after "
                        f"{now - r.t_submit:.3f}s"))
                except InvalidStateError:
                    continue
                self.telemetry.record_timeout()
                self._admission.release(r.model)
                continue
            t_pp = time.perf_counter()
            try:
                result = served.postprocess(host, i)
                # deterministic merge: identical across restore paths —
                # the chaos drill's twin-run equality leans on this
                result["session"] = r.session
                result["seq"] = r.seq
                result["detected"] = mode == "detect"
                result["state_reset"] = bool(f.reset)
            except Exception as e:
                self._fail_request(r, e)
                continue
            try:
                r.future.set_result(result)
            except InvalidStateError:
                pass
            else:
                self.telemetry.record_request(
                    queue_wait_s=t_dispatch - r.t_submit,
                    e2e_s=now - r.t_submit)
                self._admission.release(r.model)
            if r.trace and tracer.active:
                # session id on the span: per-session flows assemble in
                # the merged Perfetto timeline
                tracer.record_span(
                    "replica_queue", r.t_submit, t_dispatch, cat="serve",
                    args={"trace": r.trace, "model": served.name,
                          "session": r.session})
                tracer.record_span(
                    "postprocess", t_pp, time.perf_counter(), cat="serve",
                    args={"trace": r.trace, "session": r.session})

    def _resolve_dropped(self, r: _Request) -> None:
        self._fail_request(r, RuntimeError("engine closed"))

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout: float = 10.0, *,
              abandon_sessions: bool = False) -> None:
        """Stop the dispatcher and join its thread; pending futures fail
        with RuntimeError('engine closed'). Idempotent.

        Stateful stores flush a final snapshot per dirty session on a
        graceful close; ``abandon_sessions=True`` drops device state
        WITHOUT flushing — crash semantics for in-process replica
        kills, so recovery genuinely runs off the cadence snapshots."""
        if self._stop.is_set():
            return
        self._stop.set()
        self._paused.clear()
        self._q.put(_WAKE)
        self._thread.join(timeout)
        stores = {id(s): s for s in self._session_stores().values()}
        for s in stores.values():
            if abandon_sessions:
                s.abandon()
            else:
                s.flush()

    def __enter__(self) -> "InferenceEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
