"""Admission control: bounded queues, per-model limits, graceful shed.

The one decision this module encodes: when the engine cannot keep up,
reject new work IMMEDIATELY with a retry hint instead of queueing it
into unbounded latency. An admitted request has a bounded worst-case
wait (queue depth × observed per-row service time); an unbounded queue
turns overload into timeouts for *every* request instead of sheds for
the marginal ones — the classic load-shedding argument, and the serving
analog of the feed pipeline's bounded-queue backpressure
(``data/prefetch.py``).

:class:`ShedError` carries ``retry_after_s`` (estimated time for the
backlog to drain), which the HTTP surface maps to ``429 Retry-After``
and the JSONL surface to a ``retry_after`` field.
"""

from __future__ import annotations

import threading

__all__ = ["AdmissionController", "ShedError"]


class ShedError(RuntimeError):
    """Request rejected at admission (queue saturated). ``retry_after_s``
    estimates when capacity frees up."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Queue-depth backpressure + per-model concurrency limits.

    - ``max_queue``: total requests admitted-but-unresolved across all
      models; the engine's worst-case memory and latency bound.
    - ``per_model_limit``: optional cap per model, so one hot model
      cannot starve the rest of the host's queue budget.
    - ``slo_budget_s``: optional per-model p95 deadline budgets (the
      router's SLO table). Admission becomes SLO-aware: a request whose
      ESTIMATED wait (backlog depth x the service-time EWMA) already
      exceeds its model's budget is shed at the door — queueing it
      could only produce a late answer, and the shed's retry hint is
      honest about when capacity returns.

    ``observe_batch`` maintains an EWMA of per-row service time; the
    shed hint is ``depth × row_s`` — how long the current backlog needs
    to drain at the observed rate.
    """

    def __init__(self, max_queue: int = 256,
                 per_model_limit: int | None = None,
                 ewma_alpha: float = 0.2,
                 slo_budget_s: dict[str, float] | None = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.max_queue = max_queue
        self.per_model_limit = per_model_limit
        self.slo_budget_s = dict(slo_budget_s or {})
        self._alpha = ewma_alpha
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._total = 0
        self._row_s = 0.005  # EWMA per-row service time (seed guess)

    # -- admission -------------------------------------------------------
    def admit(self, model: str) -> None:
        """Reserve a queue slot for one request, or raise ShedError."""
        with self._lock:
            if self._total >= self.max_queue:
                raise ShedError(
                    f"queue full ({self._total}/{self.max_queue} pending)",
                    self._retry_after_locked())
            if self.per_model_limit is not None \
                    and self._counts.get(model, 0) >= self.per_model_limit:
                raise ShedError(
                    f"model {model!r} at its concurrency limit "
                    f"({self.per_model_limit})",
                    self._retry_after_locked())
            budget = self.slo_budget_s.get(model)
            if budget is not None:
                est_wait = self._total * self._row_s
                if est_wait > budget:
                    raise ShedError(
                        f"estimated queue wait {est_wait:.3f}s exceeds "
                        f"model {model!r} p95 budget {budget}s",
                        self._retry_after_locked())
            self._counts[model] = self._counts.get(model, 0) + 1
            self._total += 1

    def release(self, model: str) -> None:
        """Free one slot (request resolved: completed / timed out /
        failed / dropped at close)."""
        with self._lock:
            self._counts[model] = max(0, self._counts.get(model, 0) - 1)
            self._total = max(0, self._total - 1)

    # -- service-rate observation ---------------------------------------
    def observe_batch(self, device_s: float, rows: int) -> None:
        if rows <= 0:
            return
        with self._lock:
            per_row = device_s / rows
            self._row_s += self._alpha * (per_row - self._row_s)

    def _retry_after_locked(self) -> float:
        return round(max(0.01, self._total * self._row_s), 3)

    # -- introspection ---------------------------------------------------
    def depth(self, model: str | None = None) -> int:
        with self._lock:
            if model is not None:
                return self._counts.get(model, 0)
            return self._total

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._total,
                "max_queue": self.max_queue,
                "per_model_limit": self.per_model_limit,
                "per_model_depth": dict(self._counts),
                "ewma_row_ms": round(self._row_s * 1e3, 3),
                **({"slo_budget_s": dict(self.slo_budget_s)}
                   if self.slo_budget_s else {}),
            }
