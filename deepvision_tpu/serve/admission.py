"""Admission control: bounded queues, per-model limits, graceful shed.

The one decision this module encodes: when the engine cannot keep up,
reject new work IMMEDIATELY with a retry hint instead of queueing it
into unbounded latency. An admitted request has a bounded worst-case
wait (queue depth × observed per-row service time); an unbounded queue
turns overload into timeouts for *every* request instead of sheds for
the marginal ones — the classic load-shedding argument, and the serving
analog of the feed pipeline's bounded-queue backpressure
(``data/prefetch.py``).

:class:`ShedError` carries ``retry_after_s`` (estimated time for the
backlog to drain), which the HTTP surface maps to ``429 Retry-After``
and the JSONL surface to a ``retry_after`` field.
"""

from __future__ import annotations

import threading

__all__ = ["AdmissionController", "ShedError", "SLO_CLASSES"]

# multi-tenant SLO classes: the fraction of ``max_queue`` a tenant of
# that class may occupy while the host is under CONTENTION (someone
# else is queued too). A lone tenant always gets the whole queue —
# classes ration the shared budget, they don't strand idle capacity.
SLO_CLASSES = {"gold": 1.0, "standard": 0.8, "batch": 0.5}


class ShedError(RuntimeError):
    """Request rejected at admission (queue saturated). ``retry_after_s``
    estimates when capacity frees up."""

    def __init__(self, message: str, retry_after_s: float):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Queue-depth backpressure + per-model concurrency limits.

    - ``max_queue``: total requests admitted-but-unresolved across all
      models; the engine's worst-case memory and latency bound.
    - ``per_model_limit``: optional cap per model, so one hot model
      cannot starve the rest of the host's queue budget.
    - ``slo_budget_s``: optional per-model p95 deadline budgets (the
      router's SLO table). Admission becomes SLO-aware: a request whose
      ESTIMATED wait (backlog depth x the service-time EWMA) already
      exceeds its model's budget is shed at the door — queueing it
      could only produce a late answer, and the shed's retry hint is
      honest about when capacity returns.

    Multi-tenant isolation (``serve/tenancy.py`` story; tenant ==
    model name):

    - ``tenant_quota``: hard per-tenant queued-request caps — a noisy
      tenant hits ITS quota and sheds alone while everyone else keeps
      their slots.
    - ``slo_class``: tenant -> ``gold``/``standard``/``batch``
      (:data:`SLO_CLASSES`). Under contention (another tenant is
      queued), a tenant may only occupy its class's fraction of
      ``max_queue`` — batch traffic yields queue budget to gold
      traffic exactly when it matters and keeps the whole host when
      alone.
    - ``sheds_by_tenant`` (in :meth:`stats`) attributes every shed to
      the tenant that was rejected — the isolation-drill evidence.

    ``observe_batch`` maintains an EWMA of per-row service time; the
    shed hint is ``depth × row_s`` — how long the current backlog needs
    to drain at the observed rate.
    """

    def __init__(self, max_queue: int = 256,
                 per_model_limit: int | None = None,
                 ewma_alpha: float = 0.2,
                 slo_budget_s: dict[str, float] | None = None,
                 tenant_quota: dict[str, int] | None = None,
                 slo_class: dict[str, str] | None = None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        for tenant, cls in (slo_class or {}).items():
            if cls not in SLO_CLASSES:
                raise ValueError(
                    f"unknown SLO class {cls!r} for tenant {tenant!r}; "
                    f"choose from {sorted(SLO_CLASSES)}")
        for tenant, quota in (tenant_quota or {}).items():
            if int(quota) < 1:
                raise ValueError(
                    f"tenant {tenant!r} quota must be >= 1, got {quota}")
        self.max_queue = max_queue
        self.per_model_limit = per_model_limit
        self.slo_budget_s = dict(slo_budget_s or {})
        self.tenant_quota = {t: int(q)
                             for t, q in (tenant_quota or {}).items()}
        self.slo_class = dict(slo_class or {})
        self._alpha = ewma_alpha
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._sheds: dict[str, int] = {}
        self._total = 0
        self._row_s = 0.005  # EWMA per-row service time (seed guess)

    # -- admission -------------------------------------------------------
    def _shed_locked(self, model: str, message: str) -> ShedError:
        self._sheds[model] = self._sheds.get(model, 0) + 1
        return ShedError(message, self._retry_after_locked())

    def admit(self, model: str) -> None:
        """Reserve a queue slot for one request, or raise ShedError.
        Every rejection is attributed to ``model`` in
        ``sheds_by_tenant`` — per-tenant isolation must be provable
        from stats, not inferred."""
        with self._lock:
            if self._total >= self.max_queue:
                raise self._shed_locked(model, (
                    f"queue full ({self._total}/{self.max_queue} "
                    "pending)"))
            if self.per_model_limit is not None \
                    and self._counts.get(model, 0) >= self.per_model_limit:
                raise self._shed_locked(model, (
                    f"model {model!r} at its concurrency limit "
                    f"({self.per_model_limit})"))
            quota = self.tenant_quota.get(model)
            if quota is not None and self._counts.get(model, 0) >= quota:
                raise self._shed_locked(model, (
                    f"tenant {model!r} at its admission quota "
                    f"({quota})"))
            cls = self.slo_class.get(model)
            if cls is not None:
                mine = self._counts.get(model, 0)
                contended = self._total > mine  # someone else is queued
                share = int(SLO_CLASSES[cls] * self.max_queue)
                if contended and mine >= max(1, share):
                    raise self._shed_locked(model, (
                        f"tenant {model!r} ({cls}) at its contended "
                        f"share ({share}/{self.max_queue})"))
            budget = self.slo_budget_s.get(model)
            if budget is not None:
                est_wait = self._total * self._row_s
                if est_wait > budget:
                    raise self._shed_locked(model, (
                        f"estimated queue wait {est_wait:.3f}s exceeds "
                        f"model {model!r} p95 budget {budget}s"))
            self._counts[model] = self._counts.get(model, 0) + 1
            self._total += 1

    def release(self, model: str) -> None:
        """Free one slot (request resolved: completed / timed out /
        failed / dropped at close)."""
        with self._lock:
            self._counts[model] = max(0, self._counts.get(model, 0) - 1)
            self._total = max(0, self._total - 1)

    # -- service-rate observation ---------------------------------------
    def observe_batch(self, device_s: float, rows: int) -> None:
        if rows <= 0:
            return
        with self._lock:
            per_row = device_s / rows
            self._row_s += self._alpha * (per_row - self._row_s)

    def _retry_after_locked(self) -> float:
        return round(max(0.01, self._total * self._row_s), 3)

    # -- introspection ---------------------------------------------------
    def depth(self, model: str | None = None) -> int:
        with self._lock:
            if model is not None:
                return self._counts.get(model, 0)
            return self._total

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self._total,
                "max_queue": self.max_queue,
                "per_model_limit": self.per_model_limit,
                "per_model_depth": dict(self._counts),
                "ewma_row_ms": round(self._row_s * 1e3, 3),
                "sheds_by_tenant": dict(self._sheds),
                **({"slo_budget_s": dict(self.slo_budget_s)}
                   if self.slo_budget_s else {}),
                **({"tenant_quota": dict(self.tenant_quota)}
                   if self.tenant_quota else {}),
                **({"slo_class": dict(self.slo_class)}
                   if self.slo_class else {}),
            }
