"""Serving telemetry: per-request latency histograms + counters.

The serving counterpart of ``data/prefetch.FeedTelemetry``: where the
feed telemetry attributes *training* feed wall time to pipeline stages,
:class:`ServeTelemetry` attributes *request* wall time to the serving
stages — queue wait (admitted → dispatched), pad overhead (the fraction
of each executed batch that was zero padding up to the bucket), device
time (the compiled forward), and end-to-end latency — and keeps the
admission/outcome counters (completed / timed out / shed) that say at a
glance whether the engine is keeping up with offered load.

Since the ``obs`` subsystem exists, the primitives live there: every
latency series is an :class:`obs.metrics.Histogram` (bounded reservoir,
exact lifetime count/total, p50/p95/p99 snapshots) and every counter an
:class:`obs.metrics.Counter`, all registered into the process registry
under ``serve_*`` names — so ``GET /metrics`` (Prometheus) and the one
merged ``obs`` snapshot render the same numbers ``/stats`` reports.
The ``/stats`` JSON shape is byte-compatible with the pre-obs
implementation, and torn reads are structurally impossible now: a
histogram's (count, total, samples) triple is read under its own lock
inside ``summary()``, so even a snapshot taken outside ``_lock`` (the
old ``/stats`` hazard) can never see a count/total pair mid-record.
"""

from __future__ import annotations

import threading

from deepvision_tpu.obs.metrics import (
    Counter,
    Histogram,
    Registry,
    default_registry,
)

__all__ = ["LatencyStats", "ServeTelemetry"]


class LatencyStats:
    """Bounded-reservoir latency series with percentile snapshots —
    now a thin wrapper over :class:`obs.metrics.Histogram` (the summary
    dict is byte-compatible with the pre-obs shape).

    ``record`` takes seconds; ``summary`` reports milliseconds. The
    reservoir keeps the most recent ``maxlen`` samples (enough for
    stable p99 at serving rates) while ``count``/``total_s`` stay exact.
    """

    def __init__(self, maxlen: int = 8192,
                 hist: Histogram | None = None):
        self._hist = hist if hist is not None else Histogram(maxlen=maxlen)

    @property
    def hist(self) -> Histogram:
        return self._hist

    def record(self, seconds: float) -> None:
        self._hist.observe(seconds)

    @property
    def count(self) -> int:
        return self._hist.count

    @property
    def total_s(self) -> float:
        return self._hist.total

    def summary(self) -> dict:
        return self._hist.summary()


# exact counters, in the /stats JSON order (dict order is the contract)
_COUNTER_FIELDS = (
    "submitted",      # admitted into the queue
    "completed",      # futures resolved with a result
    "timed_out",      # deadline expired while queued
    "failed",         # postprocess/forward raised
    "shed",           # rejected at admission (backpressure)
    "batches",        # executed device batches
    "rows",           # real rows across executed batches
    "padded_rows",    # zero rows added to reach the bucket
    # dispatcher supervision (engine._supervise): a crash fails the
    # in-flight/queued futures and the loop restarts with backoff —
    # these counters are how /stats distinguishes a self-healed
    # engine from one that never faulted
    "dispatcher_crashes",
    "dispatcher_restarts",
)


class ServeTelemetry:
    """Counters + per-stage histograms for one engine's lifetime.

    Registers everything into ``registry`` (default: the process
    registry) under ``serve_*`` names; a newer engine's telemetry
    replaces an older one's registrations (latest wins), so the
    Prometheus surface always reflects the live engine. ``_lock``
    still brackets multi-field records (e.g. ``record_batch`` touching
    batches+rows+padded_rows+device_time) so ``snapshot()`` reports
    coherent cross-counter derived values like ``pad_overhead_frac``.
    """

    def __init__(self, registry: Registry | None = None):
        reg = registry if registry is not None else default_registry()
        self._lock = threading.Lock()
        self._c = {f: reg.register(f"serve_{f}", Counter())
                   for f in _COUNTER_FIELDS}
        self.queue_wait = LatencyStats(   # admitted -> batch dispatch
            hist=reg.register("serve_queue_wait", Histogram()))
        self.device_time = LatencyStats(  # compiled forward, per batch
            hist=reg.register("serve_device_time", Histogram()))
        self.e2e = LatencyStats(          # admitted -> future resolved
            hist=reg.register("serve_e2e_latency", Histogram()))

    # -- recording (dispatcher + submit threads) -------------------------
    def record_submit(self) -> None:
        with self._lock:
            self._c["submitted"].inc()

    def record_shed(self) -> None:
        with self._lock:
            self._c["shed"].inc()

    def record_timeout(self) -> None:
        with self._lock:
            self._c["timed_out"].inc()

    def record_failure(self) -> None:
        with self._lock:
            self._c["failed"].inc()

    def record_dispatcher_crash(self) -> None:
        with self._lock:
            self._c["dispatcher_crashes"].inc()

    def record_dispatcher_restart(self) -> None:
        with self._lock:
            self._c["dispatcher_restarts"].inc()

    def record_batch(self, *, bucket: int, rows: int,
                     device_s: float) -> None:
        with self._lock:
            self._c["batches"].inc()
            self._c["rows"].inc(rows)
            self._c["padded_rows"].inc(bucket - rows)
            self.device_time.record(device_s)

    def record_request(self, *, queue_wait_s: float, e2e_s: float) -> None:
        with self._lock:
            self._c["completed"].inc()
            self.queue_wait.record(queue_wait_s)
            self.e2e.record(e2e_s)

    # -- reporting -------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-able dict: counters, pad overhead, and p50/p95/p99
        blocks per stage (the serving analog of
        ``FeedTelemetry.summary``) — key-for-key identical to the
        pre-obs shape (the ``/stats`` contract)."""
        with self._lock:
            vals = {f: c.value for f, c in self._c.items()}
            executed = vals["rows"] + vals["padded_rows"]
            return {
                **vals,
                # fraction of executed device rows that were padding —
                # high values mean the ladder is too coarse (or traffic
                # too sparse) for the offered load
                "pad_overhead_frac": (
                    round(vals["padded_rows"] / executed, 4) if executed
                    else 0.0),
                "mean_batch_rows": (
                    round(vals["rows"] / vals["batches"], 2)
                    if vals["batches"] else 0.0),
                "queue_wait": self.queue_wait.summary(),
                "device_time": self.device_time.summary(),
                "e2e_latency": self.e2e.summary(),
            }


# attribute-style counter reads (eng.telemetry.batches, .timed_out, ...)
# are part of the public surface — generate one read-only property per
# counter field instead of ten hand-rolled copies
for _f in _COUNTER_FIELDS:
    setattr(ServeTelemetry, _f,
            property(lambda self, _f=_f: self._c[_f].value))
del _f
