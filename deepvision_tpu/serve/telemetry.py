"""Serving telemetry: per-request latency histograms + counters.

The serving counterpart of ``data/prefetch.FeedTelemetry``: where the
feed telemetry attributes *training* feed wall time to pipeline stages,
:class:`ServeTelemetry` attributes *request* wall time to the serving
stages — queue wait (admitted → dispatched), pad overhead (the fraction
of each executed batch that was zero padding up to the bucket), device
time (the compiled forward), and end-to-end latency — and keeps the
admission/outcome counters (completed / timed out / shed) that say at a
glance whether the engine is keeping up with offered load.

Since the ``obs`` subsystem exists, the primitives live there: every
latency series is an :class:`obs.metrics.Histogram` (bounded reservoir,
exact lifetime count/total, p50/p95/p99 snapshots) and every counter an
:class:`obs.metrics.Counter`, all registered into the process registry
under ``serve_*`` names — so ``GET /metrics`` (Prometheus) and the one
merged ``obs`` snapshot render the same numbers ``/stats`` reports.
The ``/stats`` JSON shape is byte-compatible with the pre-obs
implementation, and torn reads are structurally impossible now: a
histogram's (count, total, samples) triple is read under its own lock
inside ``summary()``, so even a snapshot taken outside ``_lock`` (the
old ``/stats`` hazard) can never see a count/total pair mid-record.
"""

from __future__ import annotations

import threading

from deepvision_tpu.obs.metrics import (
    Counter,
    Histogram,
    Registry,
    default_registry,
)

__all__ = ["LatencyStats", "ServeTelemetry", "RouterTelemetry"]


class LatencyStats:
    """Bounded-reservoir latency series with percentile snapshots —
    now a thin wrapper over :class:`obs.metrics.Histogram` (the summary
    dict is byte-compatible with the pre-obs shape).

    ``record`` takes seconds; ``summary`` reports milliseconds. The
    reservoir keeps the most recent ``maxlen`` samples (enough for
    stable p99 at serving rates) while ``count``/``total_s`` stay exact.
    """

    def __init__(self, maxlen: int = 8192,
                 hist: Histogram | None = None):
        self._hist = hist if hist is not None else Histogram(maxlen=maxlen)

    @property
    def hist(self) -> Histogram:
        return self._hist

    def record(self, seconds: float) -> None:
        self._hist.observe(seconds)

    @property
    def count(self) -> int:
        return self._hist.count

    @property
    def total_s(self) -> float:
        return self._hist.total

    def summary(self) -> dict:
        return self._hist.summary()


# exact counters, in the /stats JSON order (dict order is the contract)
_COUNTER_FIELDS = (
    "submitted",      # admitted into the queue
    "completed",      # futures resolved with a result
    "timed_out",      # deadline expired while queued
    "failed",         # postprocess/forward raised
    "shed",           # rejected at admission (backpressure)
    "batches",        # executed device batches
    "rows",           # real rows across executed batches
    "padded_rows",    # zero rows added to reach the bucket
    # dispatcher supervision (engine._supervise): a crash fails the
    # in-flight/queued futures and the loop restarts with backoff —
    # these counters are how /stats distinguishes a self-healed
    # engine from one that never faulted
    "dispatcher_crashes",
    "dispatcher_restarts",
)


class ServeTelemetry:
    """Counters + per-stage histograms for one engine's lifetime.

    Registers everything into ``registry`` (default: the process
    registry) under ``serve_*`` names; a newer engine's telemetry
    replaces an older one's registrations (latest wins), so the
    Prometheus surface always reflects the live engine. ``_lock``
    still brackets multi-field records (e.g. ``record_batch`` touching
    batches+rows+padded_rows+device_time) so ``snapshot()`` reports
    coherent cross-counter derived values like ``pad_overhead_frac``.
    """

    def __init__(self, registry: Registry | None = None):
        reg = registry if registry is not None else default_registry()
        # kept public: a replica's metrics_dump() federates THIS
        # registry up to the fleet router (obs/distributed.py), so an
        # EngineReplica's private registry is scrapeable without HTTP
        self.registry = reg
        self._lock = threading.Lock()
        self._c = {f: reg.register(f"serve_{f}", Counter())
                   for f in _COUNTER_FIELDS}
        self.queue_wait = LatencyStats(   # admitted -> batch dispatch
            hist=reg.register("serve_queue_wait", Histogram()))
        self.device_time = LatencyStats(  # compiled forward, per batch
            hist=reg.register("serve_device_time", Histogram()))
        self.e2e = LatencyStats(          # admitted -> future resolved
            hist=reg.register("serve_e2e_latency", Histogram()))

    # -- recording (dispatcher + submit threads) -------------------------
    def record_submit(self) -> None:
        with self._lock:
            self._c["submitted"].inc()

    def record_shed(self) -> None:
        with self._lock:
            self._c["shed"].inc()

    def record_timeout(self) -> None:
        with self._lock:
            self._c["timed_out"].inc()

    def record_failure(self) -> None:
        with self._lock:
            self._c["failed"].inc()

    def record_dispatcher_crash(self) -> None:
        with self._lock:
            self._c["dispatcher_crashes"].inc()

    def record_dispatcher_restart(self) -> None:
        with self._lock:
            self._c["dispatcher_restarts"].inc()

    def record_batch(self, *, bucket: int, rows: int,
                     device_s: float) -> None:
        with self._lock:
            self._c["batches"].inc()
            self._c["rows"].inc(rows)
            self._c["padded_rows"].inc(bucket - rows)
            self.device_time.record(device_s)

    def record_request(self, *, queue_wait_s: float, e2e_s: float) -> None:
        with self._lock:
            self._c["completed"].inc()
            self.queue_wait.record(queue_wait_s)
            self.e2e.record(e2e_s)

    # -- reporting -------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-able dict: counters, pad overhead, and p50/p95/p99
        blocks per stage (the serving analog of
        ``FeedTelemetry.summary``) — key-for-key identical to the
        pre-obs shape (the ``/stats`` contract)."""
        with self._lock:
            vals = {f: c.value for f, c in self._c.items()}
            executed = vals["rows"] + vals["padded_rows"]
            return {
                **vals,
                # fraction of executed device rows that were padding —
                # high values mean the ladder is too coarse (or traffic
                # too sparse) for the offered load
                "pad_overhead_frac": (
                    round(vals["padded_rows"] / executed, 4) if executed
                    else 0.0),
                "mean_batch_rows": (
                    round(vals["rows"] / vals["batches"], 2)
                    if vals["batches"] else 0.0),
                "queue_wait": self.queue_wait.summary(),
                "device_time": self.device_time.summary(),
                "e2e_latency": self.e2e.summary(),
            }


# attribute-style counter reads (eng.telemetry.batches, .timed_out, ...)
# are part of the public surface — generate one read-only property per
# counter field instead of ten hand-rolled copies
for _f in _COUNTER_FIELDS:
    setattr(ServeTelemetry, _f,
            property(lambda self, _f=_f: self._c[_f].value))
del _f


# router counters, in /stats JSON order. Sheds split by origin: an
# admission shed means the FLEET is saturated (autoscale signal), a
# circuit shed means a model's replicas keep FAILING (fast-fail), and a
# no-replica shed means every replica is draining/dead (availability
# gap the supervisor is already closing).
_ROUTER_COUNTER_FIELDS = (
    "requests",           # admitted into the router
    "completed",          # futures resolved with a result
    "failed",             # resolved with a non-shed error
    "failovers",          # attempts retried on another replica after a
                          # replica death/failure
    "hedges",             # duplicate attempts launched on a slow primary
    "hedge_wins",         # requests whose hedge resolved first
    "shed_admission",     # router admission (queue/SLO budget) rejects
    "shed_circuit",       # per-model circuit breaker open
    "shed_no_replica",    # no READY replica to route to
    "shed_replica",       # replica-side backpressure that survived the
                          # retry budget (capacity saturated, not absent)
    "replica_deaths",     # replicas observed dead (probe or attempt)
    "replica_restarts",   # replicas respawned by the supervisor
    "scale_ups",          # autoscaler added a replica
    "scale_downs",        # autoscaler drained a replica
    "sessions_migrated",  # stateful streams re-pinned to a survivor
                          # after their pinned replica died
    "session_resets",     # stream responses that DECLARED state loss
                          # (state_reset=true) — the honesty counter
                          # the chaos drill gates at zero
)


class RouterTelemetry:
    """Counters + latency histograms + autoscaler-signal gauges for one
    fleet router, registered under ``router_*`` names (default: the
    process registry, so ``GET /metrics`` and the bench ``obs`` block
    carry the fleet view). The gauges are the obs-registry signals the
    metric-driven autoscaler consumes: fleet queue-wait p95, fleet shed
    rate, and cumulative dispatcher crashes aggregated from the
    replicas' own ``/stats``.

    One router per process is the production shape and gets the default
    registry (so ``GET /metrics`` carries the fleet); a SECOND router
    in the same process must bring its own ``registry=`` — like the
    ``serve_*`` names, registration is latest-wins, and two fleets
    writing one ``router_*`` family would feed each other's autoscaler
    (``bench.py serve --sweep`` isolates its side-by-side fleets this
    way)."""

    def __init__(self, registry: Registry | None = None):
        reg = registry if registry is not None else default_registry()
        self.registry = reg  # the autoscaler reads its signals back here
        self._lock = threading.Lock()
        self._c = {f: reg.register(f"router_{f}", Counter())
                   for f in _ROUTER_COUNTER_FIELDS}
        self.e2e = LatencyStats(       # admitted -> future resolved
            hist=reg.register("router_e2e_latency", Histogram()))
        self.attempt = LatencyStats(   # one replica round-trip
            hist=reg.register("router_attempt_latency", Histogram()))
        # autoscaler signal gauges (written by the router's probe loop)
        self.replicas_ready = reg.gauge("router_replicas_ready")
        self.replicas_target = reg.gauge("router_replicas_target")
        self.queue_wait_p95_ms = reg.gauge("router_queue_wait_p95_ms")
        self.shed_rate_per_s = reg.gauge("router_shed_rate_per_s")
        self.dispatcher_crashes = reg.gauge("router_dispatcher_crashes")

    def inc(self, field: str, n: int = 1) -> None:
        self._c[field].inc(n)

    def record_attempt(self, seconds: float) -> None:
        self.attempt.record(seconds)

    def record_completed(self, e2e_s: float) -> None:
        with self._lock:
            self._c["completed"].inc()
            self.e2e.record(e2e_s)

    def snapshot(self) -> dict:
        vals = {f: c.value for f, c in self._c.items()}
        total_sheds = (vals["shed_admission"] + vals["shed_circuit"]
                       + vals["shed_no_replica"] + vals["shed_replica"])
        resolved = vals["completed"] + vals["failed"]
        return {
            **vals,
            "sheds_total": total_sheds,
            # the lived error budget: failed / resolved (sheds are the
            # DESIGNED overload response, not budget burn)
            "failed_frac": (round(vals["failed"] / resolved, 4)
                            if resolved else 0.0),
            "e2e_latency": self.e2e.summary(),
            "attempt_latency": self.attempt.summary(),
        }

    def summary_line(self) -> str:
        """Grep-stable one-liner for logs and the router smoke gate."""
        v = self.snapshot()
        return (f"[router] failovers={v['failovers']} "
                f"hedges={v['hedges']} deaths={v['replica_deaths']} "
                f"restarts={v['replica_restarts']} "
                f"sheds={v['sheds_total']} completed={v['completed']} "
                f"failed={v['failed']} "
                f"sessions_migrated={v['sessions_migrated']} "
                f"resets={v['session_resets']}")


for _f in _ROUTER_COUNTER_FIELDS:
    setattr(RouterTelemetry, _f,
            property(lambda self, _f=_f: self._c[_f].value))
del _f
