"""Serving telemetry: per-request latency histograms + counters.

The serving counterpart of ``data/prefetch.FeedTelemetry``: where the
feed telemetry attributes *training* feed wall time to pipeline stages,
:class:`ServeTelemetry` attributes *request* wall time to the serving
stages — queue wait (admitted → dispatched), pad overhead (the fraction
of each executed batch that was zero padding up to the bucket), device
time (the compiled forward), and end-to-end latency — and keeps the
admission/outcome counters (completed / timed out / shed) that say at a
glance whether the engine is keeping up with offered load.

Latencies are recorded into bounded reservoirs (a deque of the most
recent samples) so ``snapshot()`` can report p50/p95/p99 without
unbounded memory on a long-lived server; totals/counts are exact over
the process lifetime. All mutation is lock-guarded: ``submit()`` runs on
caller threads, the dispatcher records on its own thread, and ``/stats``
readers snapshot from HTTP handler threads.
"""

from __future__ import annotations

import threading
from collections import deque

__all__ = ["LatencyStats", "ServeTelemetry"]


class LatencyStats:
    """Bounded-reservoir latency series with percentile snapshots.

    ``record`` takes seconds; ``summary`` reports milliseconds. The
    reservoir keeps the most recent ``maxlen`` samples (enough for
    stable p99 at serving rates) while ``count``/``total_s`` stay exact.
    """

    def __init__(self, maxlen: int = 8192):
        self._samples: deque[float] = deque(maxlen=maxlen)
        self.count = 0
        self.total_s = 0.0

    def record(self, seconds: float) -> None:
        self._samples.append(seconds)
        self.count += 1
        self.total_s += seconds

    def summary(self) -> dict:
        import numpy as np

        if not self._samples:
            return {"count": self.count, "mean_ms": 0.0, "p50_ms": 0.0,
                    "p95_ms": 0.0, "p99_ms": 0.0, "max_ms": 0.0}
        arr = np.asarray(self._samples, dtype=np.float64) * 1e3
        p50, p95, p99 = np.percentile(arr, [50, 95, 99])
        return {
            "count": self.count,
            "mean_ms": round(self.total_s / max(1, self.count) * 1e3, 3),
            "p50_ms": round(float(p50), 3),
            "p95_ms": round(float(p95), 3),
            "p99_ms": round(float(p99), 3),
            "max_ms": round(float(arr.max()), 3),
        }


class ServeTelemetry:
    """Counters + per-stage histograms for one engine's lifetime."""

    def __init__(self):
        self._lock = threading.Lock()
        self.queue_wait = LatencyStats()   # admitted -> batch dispatch
        self.device_time = LatencyStats()  # compiled forward, per batch
        self.e2e = LatencyStats()          # admitted -> future resolved
        # exact counters
        self.submitted = 0      # admitted into the queue
        self.completed = 0      # futures resolved with a result
        self.timed_out = 0      # deadline expired while queued
        self.failed = 0         # postprocess/forward raised
        self.shed = 0           # rejected at admission (backpressure)
        self.batches = 0        # executed device batches
        self.rows = 0           # real rows across executed batches
        self.padded_rows = 0    # zero rows added to reach the bucket
        # dispatcher supervision (engine._supervise): a crash fails the
        # in-flight/queued futures and the loop restarts with backoff —
        # these counters are how /stats distinguishes a self-healed
        # engine from one that never faulted
        self.dispatcher_crashes = 0
        self.dispatcher_restarts = 0

    # -- recording (dispatcher + submit threads) -------------------------
    def record_submit(self) -> None:
        with self._lock:
            self.submitted += 1

    def record_shed(self) -> None:
        with self._lock:
            self.shed += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timed_out += 1

    def record_failure(self) -> None:
        with self._lock:
            self.failed += 1

    def record_dispatcher_crash(self) -> None:
        with self._lock:
            self.dispatcher_crashes += 1

    def record_dispatcher_restart(self) -> None:
        with self._lock:
            self.dispatcher_restarts += 1

    def record_batch(self, *, bucket: int, rows: int,
                     device_s: float) -> None:
        with self._lock:
            self.batches += 1
            self.rows += rows
            self.padded_rows += bucket - rows
            self.device_time.record(device_s)

    def record_request(self, *, queue_wait_s: float, e2e_s: float) -> None:
        with self._lock:
            self.completed += 1
            self.queue_wait.record(queue_wait_s)
            self.e2e.record(e2e_s)

    # -- reporting -------------------------------------------------------
    def snapshot(self) -> dict:
        """One JSON-able dict: counters, pad overhead, and p50/p95/p99
        blocks per stage (the serving analog of
        ``FeedTelemetry.summary``)."""
        with self._lock:
            executed = self.rows + self.padded_rows
            return {
                "submitted": self.submitted,
                "completed": self.completed,
                "timed_out": self.timed_out,
                "failed": self.failed,
                "shed": self.shed,
                "batches": self.batches,
                "rows": self.rows,
                "padded_rows": self.padded_rows,
                "dispatcher_crashes": self.dispatcher_crashes,
                "dispatcher_restarts": self.dispatcher_restarts,
                # fraction of executed device rows that were padding —
                # high values mean the ladder is too coarse (or traffic
                # too sparse) for the offered load
                "pad_overhead_frac": (
                    round(self.padded_rows / executed, 4) if executed
                    else 0.0),
                "mean_batch_rows": (
                    round(self.rows / self.batches, 2) if self.batches
                    else 0.0),
                "queue_wait": self.queue_wait.summary(),
                "device_time": self.device_time.summary(),
                "e2e_latency": self.e2e.summary(),
            }
