"""Served models: one restore + post-process path for CLI and server.

A :class:`ServedModel` wraps everything the engine needs to serve a
registry model or a restored artifact: the pure forward (a jit-able
``(variables, batch) -> outputs`` closure with the task's post-
processing folded INSIDE the traced computation — classify top-k via
``jax.lax.top_k``, YOLO decode+NMS via ``ops.yolo_postprocess``,
CenterNet peak decoding via ``ops.centernet_decode``, pose heatmap
argmax via ``ops.heatmap.decode_heatmaps`` — so the whole request path
is one fixed-shape XLA program per bucket), the restored variables, the
per-example input geometry, and a host-side ``postprocess`` that turns
batch row ``i`` into a JSON-able result.

``predict.py`` delegates its classify/detect/pose subcommands through
:func:`load_served` / :func:`restore_state`, so the one-shot CLI and the
batched engine share a single checkpoint-restore and decode code path
(previously duplicated in ``predict.py``).

Restored StableHLO artifacts (``export.load_exported``) serve too:
:func:`from_stablehlo` wraps the deserialized executable as a
ServedModel pinned to the batch size it was exported at (its bucket
ladder is exactly that one shape — ``jax.export`` artifacts are
shape-specialized).
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Callable

import numpy as np

__all__ = [
    "ServedModel", "load_served", "from_stablehlo", "restore_state",
    "model_geometry", "task_for",
]

# model name -> serving task; everything else in the registry is an
# image classifier. ("gan" serves the DCGAN *generator*: input is the
# latent z, output the sampled image.)
_TASKS = {
    "yolov3": "detect",
    "centernet": "detect",
    "hourglass104": "pose",
    "dcgan": "gan",
    "dcgan_generator": "gan",
}


def task_for(model_name: str) -> str:
    return _TASKS.get(model_name.removesuffix("_ref"), "classify")


def model_geometry(model_name: str) -> tuple[int, int]:
    """(input_size, channels) from the model's training config so
    restored checkpoints see the shapes they were trained with."""
    from deepvision_tpu.train.configs import TRAINING_CONFIG

    cfg = TRAINING_CONFIG.get(model_name.removesuffix("_ref"), {})
    return cfg.get("input_size", 224), cfg.get("channels", 3)


def input_scale(model_name: str) -> str:
    """Pixel-scaling convention for this model's inputs (mirrors the
    training pipeline): 'unit' for grayscale nets, 'torch' for
    PT-lineage configs, 'imagenet' otherwise, 'tanh' for the
    detection/pose/GAN families."""
    if task_for(model_name) != "classify":
        return "tanh"
    from deepvision_tpu.train.configs import TRAINING_CONFIG

    cfg = TRAINING_CONFIG.get(model_name.removesuffix("_ref"), {})
    if cfg.get("channels", 3) == 1:
        return "unit"  # grayscale nets (lenet5)
    return "torch" if cfg.get("augment", "tf") == "pt" else "imagenet"


# ------------------------------------------------------------- restore


def restore_state(model_name: str, workdir: str | None, sample,
                  epoch=None, **model_kw):
    """Build an inference TrainState and restore the latest (or a
    specific) checkpoint epoch from ``workdir`` — the single restore
    path shared by ``predict.py`` and the serving engine.

    ``epoch``: a specific saved epoch to restore (default latest) —
    with ``--keep-best`` retention the best checkpoint is often not the
    newest, so offline eval must be able to target it."""
    import jax.numpy as jnp
    import optax

    from deepvision_tpu.models import get_model
    from deepvision_tpu.train.state import create_train_state

    model = get_model(model_name, dtype=jnp.float32, **model_kw)
    # Throwaway tx: restore_inference never touches opt_state, so the
    # template needn't match the training optimizer (which varies per
    # config: momentum SGD, adam, plateau-wrapped schedules).
    state = create_train_state(model, optax.sgd(0.1), sample)
    if workdir and Path(f"{workdir}/ckpt").exists():
        from deepvision_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(f"{workdir}/ckpt")
        if mgr.latest_epoch() is not None:
            state, meta = mgr.restore_inference(state, epoch)
            print(f"restored epoch {meta['epoch']} from {workdir}/ckpt")
            mgr.close()
            return state
        mgr.close()
    if epoch is not None:
        # an EXPLICIT epoch request must not silently score random
        # weights (near-zero metrics recorded as that epoch's result)
        raise FileNotFoundError(
            f"requested epoch {epoch} but no checkpoint dir under "
            f"{workdir!r}")
    print("no checkpoint found — running freshly initialized weights")
    return state


def _state_variables(state) -> dict:
    variables = {"params": state.params}
    if state.batch_stats:
        variables["batch_stats"] = state.batch_stats
    return variables


# ---------------------------------------------------------- ServedModel


@dataclasses.dataclass
class ServedModel:
    """One model the engine can serve. ``forward`` is pure/jit-able
    (``(variables, batch) -> outputs``); ``postprocess`` runs on the
    host on fetched outputs and extracts row ``i`` as a JSON-able dict.
    ``buckets`` overrides the engine's ladder (StableHLO artifacts are
    pinned to the batch they were exported at); ``precompiled`` is a
    ready runner that bypasses compilation entirely."""

    name: str
    task: str
    forward: Callable
    variables: Any
    input_shape: tuple[int, ...]
    postprocess: Callable
    input_dtype: Any = np.float32
    buckets: tuple[int, ...] | None = None
    scale: str = "unit"
    precompiled: Callable | None = None
    _direct: Callable | None = dataclasses.field(
        default=None, repr=False, compare=False)
    # tenancy: the live WeightsEdition (``serve.tenancy``) once the
    # engine adopts this model as a tenant. Runners compiled while an
    # edition is attached read weights through it at call time, which
    # is what makes LRU eviction and zero-drop hot-swap possible.
    edition: Any = dataclasses.field(
        default=None, repr=False, compare=False)
    _fingerprint: str | None = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def dtype_str(self) -> str:
        return str(np.dtype(self.input_dtype))

    def weights_fingerprint(self) -> str:
        """Content hash of the weights (cached): the compile-cache /
        artifact-store key component that keeps an executable compiled
        against one weights generation from ever pairing with another.
        Content-derived, so a respawned replica restoring the same
        checkpoint agrees with the store on disk."""
        if self.edition is not None:
            return self.edition.fingerprint
        if self._fingerprint is None:
            from deepvision_tpu.serve.tenancy import fingerprint_variables

            self._fingerprint = fingerprint_variables(self.variables)
        return self._fingerprint

    # -- engine path -----------------------------------------------------
    def as_stage(self):
        """The compiled unit behind this model: a ``pipeline.ModelStage``
        carrying the pure forward + variables + explicit input/output
        avals — what a serving DAG composes. ``compile_for`` delegates
        here so the single-model and pipeline paths share one AOT
        compile recipe. The stage snapshots the CURRENT weights edition:
        runners compiled from it read that edition at call time."""
        from deepvision_tpu.serve.pipeline import ModelStage

        ed = self.edition
        return ModelStage(
            name=self.name, forward=self.forward,
            variables=self.variables, input_shape=self.input_shape,
            input_dtype=self.input_dtype, precompiled=self.precompiled,
            pinned_buckets=self.buckets,
            variables_ref=(lambda: ed.variables) if ed is not None
            else None,
            # config-time hash of host weights (cached after first
            # call), not a fetch on the DAG execution path
            fingerprint=self.weights_fingerprint(),  # jaxlint: disable=JX127
        )

    def in_avals(self, bucket: int):
        return self.as_stage().in_avals(bucket)

    def out_avals(self, bucket: int):
        """Abstract output pytree at ``bucket`` (``jax.eval_shape``, no
        compile) — the seam a pipeline validator type-checks DAG edges
        against, mirroring ``export.py``'s artifact metadata."""
        return self.as_stage().out_avals(bucket)

    def compile_for(self, bucket: int, mesh) -> Callable:
        """AOT-compile the forward at ``(bucket, *input_shape)`` over
        ``mesh`` — batch sharded on the data axis, variables replicated,
        the input buffer donated — and return a runner
        ``x_device -> device outputs``. StableHLO-backed models return
        their deserialized executable (already compiled, one shape)."""
        return self.as_stage().compile(bucket, mesh, donate=True)

    def export_bytes(self, bucket: int) -> bytes:
        """Serialize the whole request program at ``bucket`` —
        forward + in-graph post-processing with the CURRENT weights
        baked in as constants — as StableHLO bytes. What the serve
        artifact store persists (keyed by this model's
        ``weights_fingerprint``), so a fresh replica deserializes
        instead of re-tracing."""
        from deepvision_tpu.export import export_callable

        variables = self.variables
        forward = self.forward

        def fn(x):
            return forward(variables, x)

        return export_callable(fn, self.in_avals(bucket))

    # -- direct (engine-less) path: the one-shot CLI ---------------------
    def run(self, batch) -> Any:
        """Direct host-side call for the one-shot CLI path (no queue, no
        buckets): jit once per instance, fetch outputs to host."""
        import jax

        if self.precompiled is not None:
            return jax.device_get(self.precompiled(np.asarray(batch)))
        if self._direct is None:
            self._direct = jax.jit(self.forward)
        return jax.device_get(
            self._direct(self.variables, np.asarray(batch)))

    def run_one(self, x) -> dict:
        """Single example (no batch dim) -> this task's result dict."""
        return self.postprocess(self.run(np.asarray(x)[None]), 0)


# ------------------------------------------------------- task forwards


def _classify_forward(apply_fn, top_k: int):
    import jax
    import jax.numpy as jnp

    def forward(variables, x):
        logits = apply_fn(variables, x, train=False)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]  # aux-head models (inception) -> main
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        top_probs, top_classes = jax.lax.top_k(probs, top_k)
        return {"probs": top_probs, "classes": top_classes}

    return forward


def _classify_post(host: dict, i: int) -> dict:
    return {"classes": np.asarray(host["classes"][i]).tolist(),
            "probs": np.asarray(host["probs"][i]).tolist()}


def _yolo_forward(apply_fn, num_classes: int, score_thresh: float,
                  iou_thresh: float):
    from deepvision_tpu.ops.yolo_postprocess import yolo_postprocess

    def forward(variables, x):
        preds = apply_fn(variables, x, train=False)
        boxes, scores, classes, valid, _ = yolo_postprocess(
            preds, num_classes,
            score_thresh=score_thresh, iou_thresh=iou_thresh,
        )
        return {"boxes": boxes, "scores": scores, "classes": classes,
                "valid": valid}

    return forward


def _detect_post(host: dict, i: int) -> dict:
    keep = np.asarray(host["valid"][i]).astype(bool)
    return {
        # normalized corner boxes (x1, y1, x2, y2)
        "boxes": np.asarray(host["boxes"][i])[keep].tolist(),
        "scores": np.asarray(host["scores"][i])[keep].tolist(),
        "classes": np.asarray(host["classes"][i])[keep].tolist(),
    }


def _centernet_forward(apply_fn, score_thresh: float, top_k: int = 100):
    from deepvision_tpu.ops.centernet_decode import decode_centernet
    from deepvision_tpu.ops.iou import xywh_to_corners

    def forward(variables, x):
        heat, wh, off = apply_fn(variables, x, train=False)[-1]
        det = decode_centernet(heat, wh, off, top_k=top_k)
        # normalize to the same corner-box contract as the YOLO head
        det["boxes"] = xywh_to_corners(det["boxes"])
        det["valid"] = det["scores"] > score_thresh
        return det

    return forward


def _pose_forward(apply_fn):
    from deepvision_tpu.ops.heatmap import decode_heatmaps

    def forward(variables, x):
        heatmaps = apply_fn(variables, x, train=False)[-1]  # last stack
        kx, ky, conf = decode_heatmaps(heatmaps)
        return {"x": kx, "y": ky, "conf": conf}

    return forward


def _pose_post(host: dict, i: int) -> dict:
    return {"joints": np.stack(
        [np.asarray(host["x"][i]), np.asarray(host["y"][i]),
         np.asarray(host["conf"][i])], axis=-1).tolist()}


def _gan_post(host: dict, i: int) -> dict:
    return {"image": np.asarray(host["image"][i]).tolist()}


# --------------------------------------------------------------- loaders


def load_served(
    name: str,
    workdir: str | None = None,
    *,
    task: str | None = None,
    epoch: int | None = None,
    input_size: int | None = None,
    num_classes: int | None = None,
    top_k: int = 5,
    score_thresh: float = 0.5,
    iou_thresh: float = 0.5,
    num_heatmaps: int = 16,
    **model_kw,
) -> ServedModel:
    """Restore registry model ``name`` from ``workdir`` (or fresh
    weights) and wrap it as a :class:`ServedModel` for its task."""
    task = task or task_for(name)
    size, channels = model_geometry(name)
    if input_size is not None:
        size = input_size

    if task == "gan":
        return _load_gan_served(name, workdir, epoch=epoch)

    from deepvision_tpu.train.configs import TRAINING_CONFIG

    if num_classes is None:
        num_classes = TRAINING_CONFIG.get(
            name.removesuffix("_ref"), {}).get("num_classes", 1000)

    if task == "classify":
        sample = np.zeros((1, size, size, channels), np.float32)
        state = restore_state(name, workdir, sample, epoch,
                              num_classes=num_classes, **model_kw)
        forward = _classify_forward(state.apply_fn, top_k)
        post = _classify_post
    elif task == "detect":
        sample = np.zeros((1, size, size, channels), np.float32)
        state = restore_state(name, workdir, sample, epoch,
                              num_classes=num_classes, **model_kw)
        if name.removesuffix("_ref") == "centernet":
            forward = _centernet_forward(state.apply_fn, score_thresh)
        else:
            forward = _yolo_forward(state.apply_fn, num_classes,
                                    score_thresh, iou_thresh)
        post = _detect_post
    elif task == "pose":
        sample = np.zeros((1, size, size, channels), np.float32)
        state = restore_state(name, workdir, sample, epoch,
                              num_heatmaps=num_heatmaps, **model_kw)
        forward = _pose_forward(state.apply_fn)
        post = _pose_post
    else:
        raise ValueError(f"unknown serving task {task!r}")

    return ServedModel(
        name=name, task=task, forward=forward,
        variables=_state_variables(state),
        input_shape=(size, size, channels), postprocess=post,
        scale=input_scale(name),
    )


def _load_gan_served(name: str, workdir: str | None, *,
                     epoch: int | None = None) -> ServedModel:
    """DCGAN generator as a served model: input z, output image."""
    from deepvision_tpu.models import get_model
    from deepvision_tpu.train.gan import create_dcgan_state

    state = create_dcgan_state(
        get_model("dcgan_generator"), get_model("dcgan_discriminator")
    )
    restored = False
    if workdir and Path(f"{workdir}/ckpt").exists():
        from deepvision_tpu.train.checkpoint import CheckpointManager

        mgr = CheckpointManager(f"{workdir}/ckpt")
        if mgr.latest_epoch() is not None:
            state, meta = mgr.restore_inference(state, epoch)
            print(f"restored epoch {meta['epoch']} from {workdir}/ckpt")
            restored = True
        mgr.close()
    if epoch is not None and not restored:
        # same invariant as restore_state: an EXPLICIT epoch request
        # must not silently serve random weights
        raise FileNotFoundError(
            f"requested epoch {epoch} but no checkpoint under "
            f"{workdir!r}")
    g_apply = state.g_apply
    variables = {"params": state.params, "batch_stats": state.batch_stats}

    def forward(v, z):
        image = g_apply(
            {"params": v["params"]["generator"],
             "batch_stats": v["batch_stats"]["generator"]},
            z, train=False,
        )
        return {"image": image}

    return ServedModel(
        name=name, task="gan", forward=forward, variables=variables,
        input_shape=(state.noise_dim,), postprocess=_gan_post,
        scale="tanh",
    )


def from_stablehlo(path: str | Path, *, name: str | None = None,
                   task: str = "classify", top_k: int = 5) -> ServedModel:
    """Wrap an ``export.py`` StableHLO artifact as a ServedModel.

    The artifact is shape-specialized at export time, so its bucket
    ladder is exactly the exported batch size; the engine serves it with
    zero compiles (the deserialized executable IS the runner)."""
    from deepvision_tpu.export import load_exported

    fn = load_exported(path)
    (aval,) = fn.in_avals  # export_forward exports a single-arg forward
    batch, *input_shape = aval.shape
    name = name or Path(path).stem

    if task == "classify":
        def post(host, i):
            out = host
            if isinstance(out, (tuple, list)):
                out = out[0]
            logits = np.asarray(out[i])
            top = np.argsort(logits)[::-1][:top_k]
            probs = np.exp(logits - logits.max())
            probs /= probs.sum()
            return {"classes": top.tolist(),
                    "probs": probs[top].tolist()}
    else:
        raise ValueError(
            f"StableHLO serving currently supports classify heads only, "
            f"got task {task!r}")

    def precompiled(x):
        return fn(x)

    return ServedModel(
        name=name, task=task, forward=lambda _v, x: fn(x), variables=None,
        input_shape=tuple(input_shape), postprocess=post,
        input_dtype=np.dtype(aval.dtype), buckets=(int(batch),),
        precompiled=precompiled,
    )
