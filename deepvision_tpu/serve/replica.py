"""Serving replicas: the units the fleet router supervises.

A replica is one engine's worth of serving capacity behind a uniform,
transport-agnostic surface the :class:`~deepvision_tpu.serve.router.
FleetRouter` can route to, probe, drain, and kill:

- :class:`EngineReplica` wraps an in-process
  :class:`~deepvision_tpu.serve.engine.InferenceEngine` — compiles in
  milliseconds on the toy test models, so the router's lifecycle tests
  (draining, failover, autoscaling, chaos) stay in the fast tier.
- :class:`ProcessReplica` spawns ``serve.py --http 0 --port-file ...``
  as a child process and talks HTTP — the production topology
  (process-per-replica: one crash, one SIGKILL, one OOM takes out ONE
  replica's capacity, never the fleet), and the only backend a chaos
  drill can *actually* SIGKILL (``bench.py serve --sweep``,
  ``make router-smoke``).

The contract every backend honors:

- ``request()`` either returns the result dict or raises: a
  :class:`ReplicaDeadError` (replica gone — the router fails over), a
  :class:`~deepvision_tpu.serve.admission.ShedError` (replica-side
  backpressure, carries ``retry_after_s``), a ``TimeoutError`` (the
  replica's own deadline machinery), or ``ValueError`` (client error —
  bad shape/model; NOT retryable on another replica).
- ``probe()`` returns the replica's health dict (``status`` of ``"ok"``
  or ``"recovering"``) or raises :class:`ReplicaDeadError`.
- ``kill()`` is abrupt (SIGKILL / fail-everything close); ``stop()``
  is the graceful twin. Both are idempotent. A killed replica is
  single-use: the router respawns a FRESH replica via its factory
  instead of resurrecting the corpse.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Callable

import numpy as np

from deepvision_tpu.serve.admission import ShedError

__all__ = ["ReplicaDeadError", "EngineReplica", "ProcessReplica"]


class ReplicaDeadError(RuntimeError):
    """The replica is gone (process died / engine closed / connection
    refused): the router should mark it dead and fail the attempt over
    to a healthy replica."""


class EngineReplica:
    """In-process replica: one :class:`InferenceEngine` built from a
    ``models_factory`` at :meth:`start`. ``kill()`` models abrupt death
    (the engine closes, failing every in-flight future — exactly what
    the router's failover must absorb)."""

    def __init__(self, replica_id: str,
                 models_factory: Callable[[], list],
                 **engine_kw):
        self.replica_id = replica_id
        self._models_factory = models_factory
        self._engine_kw = dict(engine_kw)
        self._engine = None
        self._dead = False

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        from deepvision_tpu.serve.engine import InferenceEngine
        from deepvision_tpu.serve.telemetry import ServeTelemetry

        from deepvision_tpu.obs.metrics import Registry

        # private registry per replica: N in-process engines must not
        # fight over the process-default serve_* names (latest-wins
        # would hide every replica but one from the autoscaler signals)
        kw = dict(self._engine_kw)
        kw.setdefault("telemetry", ServeTelemetry(registry=Registry()))
        self._engine = InferenceEngine(self._models_factory(), **kw)

    def stop(self) -> None:
        if self._engine is not None:
            self._engine.close()

    def kill(self) -> None:
        """Abrupt death: in-flight futures fail with 'engine closed',
        subsequent requests/probes raise :class:`ReplicaDeadError`.
        Session state is ABANDONED, not flushed — an in-process kill
        must exercise the same cadence-snapshot recovery a SIGKILL
        would, or the chaos drill proves nothing."""
        self._dead = True
        if self._engine is not None:
            self._engine.close(abandon_sessions=True)

    # -- serving surface -------------------------------------------------
    def request(self, model: str | None, x, *,
                timeout_s: float | None = None,
                trace: str | None = None,
                session: str | None = None,
                seq: int | None = None) -> dict:
        if self._dead or self._engine is None:
            raise ReplicaDeadError(f"{self.replica_id}: replica is dead")
        try:
            fut = self._engine.submit(x, model=model, timeout_s=timeout_s,
                                      trace=trace, session=session,
                                      seq=seq)
            return fut.result(
                timeout=timeout_s + 1.0 if timeout_s is not None else None)
        except (ShedError, TimeoutError, ValueError):
            raise
        except RuntimeError as e:
            # "closed" = the engine is permanently gone: a death
            # verdict is right. A dispatcher CRASH is not — the PR 4
            # supervisor is already restarting it (probe reports
            # "recovering", the router drains); condemning here would
            # kill a self-healing engine and pay a full respawn.
            if "closed" in str(e):
                raise ReplicaDeadError(
                    f"{self.replica_id}: {e}") from e
            raise

    def probe(self) -> dict:
        if self._dead or self._engine is None:
            raise ReplicaDeadError(f"{self.replica_id}: replica is dead")
        return self._engine.health()

    def stats(self) -> dict:
        if self._dead or self._engine is None:
            raise ReplicaDeadError(f"{self.replica_id}: replica is dead")
        return self._engine.stats()

    def metrics_dump(self) -> dict:
        """This replica's typed registry dump (histogram reservoirs
        included) — the federation scrape, straight off the engine's
        private registry."""
        if self._dead or self._engine is None:
            raise ReplicaDeadError(f"{self.replica_id}: replica is dead")
        return self._engine.telemetry.registry.dump()


class ProcessReplica:
    """Child-process replica: spawns ``serve.py --http 0 --port-file``
    and talks plain HTTP (`POST /v1/predict`, `GET /healthz`,
    `GET /stats`). ``cpu_affinity`` (a set of core ids, Linux only) pins
    the child so a fleet bench measures replica scaling, not N processes
    thrashing one core."""

    def __init__(self, replica_id: str, argv: list[str], *,
                 startup_timeout_s: float = 240.0,
                 cpu_affinity: set[int] | None = None,
                 env: dict | None = None,
                 stop_event: threading.Event | None = None):
        self.replica_id = replica_id
        self._argv = list(argv)
        self._startup_timeout_s = startup_timeout_s
        self._affinity = cpu_affinity
        self._env = env
        self._stop_event = stop_event or threading.Event()
        self._proc: subprocess.Popen | None = None
        self._port: int | None = None
        self._dead = False
        self._log_path: Path | None = None
        # per-thread keep-alive connection to this replica (the server
        # speaks HTTP/1.1): a router attempt thread pays TCP setup once,
        # not once per request
        self._conns = threading.local()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        tmp = Path(tempfile.mkdtemp(prefix=f"dvt-replica-{self.replica_id}-"))
        port_file = tmp / "port"
        self._log_path = tmp / "replica.log"
        argv = self._argv + ["--port-file", str(port_file)]
        env = dict(self._env if self._env is not None else os.environ)
        with open(self._log_path, "wb") as log:
            self._proc = subprocess.Popen(
                argv, stdout=log, stderr=subprocess.STDOUT,
                stdin=subprocess.DEVNULL, env=env)
        if self._affinity and hasattr(os, "sched_setaffinity"):
            try:
                os.sched_setaffinity(self._proc.pid, self._affinity)
            except OSError:
                pass  # affinity is an optimization, never a failure
        deadline = time.monotonic() + self._startup_timeout_s
        # stop-responsive poll: the port file appears once the server is
        # bound, /healthz 200 once warmup compiles finished
        while True:
            if self._proc.poll() is not None:
                raise ReplicaDeadError(
                    f"{self.replica_id}: exited rc={self._proc.returncode} "
                    f"during startup (log: {self._log_path})")
            if self._stop_event.is_set():
                self.kill()
                raise ReplicaDeadError(
                    f"{self.replica_id}: startup aborted by shutdown")
            if self._port is None and port_file.exists():
                try:
                    self._port = int(port_file.read_text().strip())
                except ValueError:
                    self._port = None  # partially written: retry
            if self._port is not None:
                try:
                    if self.probe().get("status") == "ok":
                        return
                except (ReplicaDeadError, OSError):
                    pass
            if time.monotonic() > deadline:
                self.kill()
                raise ReplicaDeadError(
                    f"{self.replica_id}: not ready within "
                    f"{self._startup_timeout_s:.0f}s (log: {self._log_path})")
            self._stop_event.wait(0.1)

    def stop(self, grace_s: float = 5.0) -> None:
        if self._proc is None:
            return
        self._dead = True
        if self._proc.poll() is None:
            self._proc.terminate()
            try:
                self._proc.wait(grace_s)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait(grace_s)

    def kill(self) -> None:
        """SIGKILL — the real thing, not a simulation."""
        self._dead = True
        if self._proc is not None and self._proc.poll() is None:
            self._proc.kill()
            try:
                self._proc.wait(5.0)
            except subprocess.TimeoutExpired:
                pass

    @property
    def pid(self) -> int | None:
        return self._proc.pid if self._proc is not None else None

    # -- HTTP plumbing ---------------------------------------------------
    def _http(self, method: str, path: str, body: str | None = None,
              timeout_s: float = 10.0, headers: dict | None = None):
        import http.client

        if self._dead or self._port is None:
            raise ReplicaDeadError(f"{self.replica_id}: replica is dead")
        if self._proc is not None and self._proc.poll() is not None:
            raise ReplicaDeadError(
                f"{self.replica_id}: process exited "
                f"rc={self._proc.returncode}")
        conn = getattr(self._conns, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection("127.0.0.1", self._port,
                                              timeout=timeout_s)
            self._conns.conn = conn
        else:
            conn.timeout = timeout_s
            if conn.sock is not None:
                conn.sock.settimeout(timeout_s)
        try:
            conn.request(method, path, body, headers=headers or {})
            resp = conn.getresponse()
            return resp.status, dict(resp.getheaders()), resp.read()
        except TimeoutError as e:
            # a client-side read timeout means SLOW, not DEAD: the
            # router must treat it as a latency failure (breaker food,
            # retryable) — declaring a healthy-but-slow replica dead
            # would turn a latency event into a respawn cascade. The
            # half-read keep-alive socket is unusable either way.
            self._drop_conn(conn)
            raise TimeoutError(
                f"{self.replica_id}: no response within {timeout_s}s"
            ) from e
        except (ConnectionError, OSError,
                http.client.HTTPException) as e:
            # a broken keep-alive socket is not reusable; drop it so
            # the next call (possibly post-restart) reconnects fresh
            self._drop_conn(conn)
            if self._proc is not None and self._proc.poll() is None:
                # the process is still alive: one dropped connection
                # (a crashed handler thread, a reset keep-alive) is a
                # request failure — breaker food, retryable — not a
                # death verdict. Condemning here would SIGKILL a live
                # replica and pay a full respawn+recompile for what
                # may be a single poison request.
                raise RuntimeError(
                    f"{self.replica_id}: request failed "
                    f"({type(e).__name__}: {e}); process alive") from e
            raise ReplicaDeadError(
                f"{self.replica_id}: {type(e).__name__}: {e}") from e

    def _drop_conn(self, conn) -> None:
        self._conns.conn = None
        try:
            conn.close()
        except Exception:
            pass

    # -- serving surface -------------------------------------------------
    def request(self, model: str | None, x, *,
                timeout_s: float | None = None,
                trace: str | None = None,
                session: str | None = None,
                seq: int | None = None) -> dict:
        import base64

        # binary wire format (serve.py `input_b64`): base64 raw bytes
        # beat nested float lists ~20x on both encode and decode — at
        # fleet scale the router's per-request JSON cost IS capacity
        x = np.ascontiguousarray(np.asarray(x, np.float32))
        payload: dict = {
            "input_b64": base64.b64encode(x.tobytes()).decode("ascii"),
            "shape": list(x.shape),
            "dtype": "float32",
        }
        if model is not None:
            payload["model"] = model
        if session is not None:
            # stateful stream frame: the child's SessionStore threads
            # state by (session, seq)
            payload["session"] = session
            payload["seq"] = seq
        if timeout_s is not None:
            # carry the router's remaining deadline to the child, so
            # the replica stops working a request the router has
            # already timed out or hedged away — without this, every
            # losing attempt still burns a full replica slot under the
            # child's blanket --timeout-s
            payload["timeout_s"] = round(timeout_s, 3)
        req_headers = None
        if trace is not None:
            # the distributed-trace hop: the child stamps its
            # queue/device/postprocess spans with this id, so the
            # merged fleet trace links router attempt -> replica work
            from deepvision_tpu.obs.distributed import TRACE_HEADER

            req_headers = {TRACE_HEADER: trace}
        status, headers, body = self._http(
            "POST", "/v1/predict", json.dumps(payload),
            timeout_s=(timeout_s or 30.0) + 1.0, headers=req_headers)
        try:
            data = json.loads(body)
        except ValueError:
            data = {"error": body.decode(errors="replace")}
        if status == 200:
            return data["result"]
        if status == 429:
            raise ShedError(data.get("error", "shed"),
                            float(data.get("retry_after", 0.05)))
        if status == 504:
            raise TimeoutError(data.get("error", "deadline expired"))
        if status == 400:
            raise ValueError(data.get("error", "bad request"))
        # 5xx / unknown: the replica ANSWERED (it is alive) — a
        # request-level failure the router may retry elsewhere, never
        # a death verdict
        raise RuntimeError(
            f"{self.replica_id}: HTTP {status}: {data.get('error')}")

    def probe(self) -> dict:
        status, headers, body = self._http("GET", "/healthz",
                                           timeout_s=5.0)
        try:
            health = json.loads(body)
        except ValueError:
            health = {}
        if status == 200:
            health.setdefault("status", "ok")
        else:
            health.setdefault("status", "recovering")
        return health

    def stats(self) -> dict:
        status, _h, body = self._http("GET", "/stats", timeout_s=5.0)
        if status != 200:
            raise ReplicaDeadError(
                f"{self.replica_id}: /stats HTTP {status}")
        return json.loads(body)

    def metrics_dump(self) -> dict:
        """The child's typed registry dump over HTTP
        (``GET /metrics.json``) — what the router federates into its
        fleet-wide ``/metrics``."""
        status, _h, body = self._http("GET", "/metrics.json",
                                      timeout_s=5.0)
        if status != 200:
            raise RuntimeError(
                f"{self.replica_id}: /metrics.json HTTP {status}")
        return json.loads(body)


def replica_argv(model_specs: list[str], *, buckets: str | None = None,
                 artifact_specs: list[str] | None = None,
                 store: str | None = None,
                 extra: list[str] | None = None) -> list[str]:
    """argv for a ``ProcessReplica`` child: this interpreter running the
    repo's ``serve.py`` in HTTP mode on an ephemeral port.

    ``store``: a shared AOT artifact-store directory (``--store``) —
    every child of the fleet warms its executables from the same disk
    cache, so a respawned replica skips the compile storm the first
    generation paid."""
    serve_py = Path(__file__).resolve().parent.parent.parent / "serve.py"
    argv = [sys.executable, str(serve_py), "--http", "0"]
    for spec in model_specs:
        argv += ["-m", spec]
    for spec in artifact_specs or []:
        argv += ["--artifact", spec]
    if buckets:
        argv += ["--buckets", buckets]
    if store:
        argv += ["--store", str(store)]
    argv += list(extra or [])
    return argv
