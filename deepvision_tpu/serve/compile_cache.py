"""Shape-bucketed executable cache: compile once, serve forever.

Serving traffic arrives at arbitrary batch sizes, but XLA executables
are shape-specialized — a naive per-request ``jit`` retraces on every
new batch size and the chip spends its time in the compiler instead of
the MXU (jaxlint JX110 flags exactly that pattern). The engine instead
pads every micro-batch up to a fixed bucket ladder and runs a
pre-compiled executable per ``(model, bucket, dtype, weights
fingerprint)`` key, all of them compiled eagerly at startup
(:meth:`CompileCache.warmup` via ``engine.InferenceEngine``) so no
request ever pays a trace. The weights fingerprint exists for hot-swap
coherence: swapping a tenant's weights changes its fingerprint, so a
stale executable compiled against the old weights can never be *hit*
for the new ones — the swap path pre-compiles and :meth:`install`\\ s
the new ladder, then :meth:`drop_where` retires the old keys.

The cache is an LRU so a long-lived multi-model host with a rotating
model set stays bounded; with the default ladder (4 buckets × a few
models) nothing ever evicts. Hit/miss/eviction counters feed the
telemetry ``/stats`` snapshot — after warmup, ``misses`` must stay
frozen (the acceptance tripwire for "no request triggers a compile").
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Hashable

__all__ = ["CompileCache"]


class CompileCache:
    """LRU of compiled executables keyed by ``(model, bucket, dtype,
    weights fingerprint)``.

    ``build`` callables passed to :meth:`get_or_build` return the ready
    runner (typically an AOT ``jit(...).lower(...).compile()`` wrapper);
    the cache never inspects them. Builds run under the lock — the
    builders are only ever invoked from the engine's warmup and its
    single dispatcher thread, and serializing them is the point (two
    concurrent compiles of the same key would both pay the trace).
    """

    def __init__(self, max_entries: int = 64):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = max_entries
        self._entries: OrderedDict[Hashable, Callable] = OrderedDict()
        # reentrant: a pipeline's whole-DAG builder runs under the lock
        # and compiles its per-stage executables through this same
        # cache (nested get_or_build from the same thread)
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._frozen = False

    def freeze(self) -> None:
        """Turn "misses must stay frozen after warmup" from a telemetry
        tripwire into a hard guarantee: any later miss raises instead of
        compiling. The engine calls this after an end-to-end ``warm()``
        (``freeze_cache=True``) — a pipeline request hitting a cold
        (stage, bucket, dtype) key is a warmup-coverage bug, and paying
        the trace silently would hide it as tail latency."""
        with self._lock:
            self._frozen = True

    def get_or_build(self, key: Hashable, build: Callable[[], Callable]):
        with self._lock:
            if key in self._entries:
                self.hits += 1
                self._entries.move_to_end(key)
                return self._entries[key]
            if self._frozen:
                raise RuntimeError(
                    f"compile cache is frozen after warmup but key "
                    f"{key!r} missed — a request would have paid a "
                    "hidden trace/compile (warmup coverage bug)")
            self.misses += 1
            runner = build()
            self._entries[key] = runner
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1
            return runner

    def install(self, key: Hashable, runner: Callable) -> None:
        """Insert a ready executable, bypassing the miss path. This is
        the deliberate post-warmup mutation channel — hot-swap
        pre-compiles a tenant's new-fingerprint ladder off the dispatch
        path and installs it here, and the artifact store installs
        deserialized StableHLO runners at warm — so it works on a
        FROZEN cache and counts as neither hit nor miss (the
        miss-freeze tripwire keeps meaning "a request paid a trace")."""
        with self._lock:
            self._entries[key] = runner
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def drop_where(self, pred: Callable[[Hashable], bool]) -> int:
        """Remove entries whose key satisfies ``pred`` (hot-swap drops
        the old fingerprint's executables — unreachable once the key
        changed). Returns the count; not counted as LRU evictions."""
        with self._lock:
            stale = [k for k in self._entries if pred(k)]
            for k in stale:
                del self._entries[k]
            return len(stale)

    def contains(self, key: Hashable) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "frozen": self._frozen,
            }
