"""Crash-safe stateful sessions: device-resident stream state that
survives replica death.

The fleet tier (PR 6) and the pipeline tier (PR 18) are stateless — a
replica SIGKILL loses nothing a retry can't rebuild. Streaming
workloads (ROADMAP item 4) break that: tracking-by-detection carries a
per-stream track slate from frame to frame, and losing it mid-stream is
a client-visible hard reset. This module makes that state a first-class
recoverable resource, the same way PR 4 did for training checkpoints:

- :class:`SessionStore` pins per-session device state (track slates —
  flat ``{name: array}`` pytrees) with TTL eviction and a bounded
  capacity that sheds NEW sessions at the door (old state is never
  dropped to make room). On a configurable frame cadence it writes
  incremental host-side snapshots, crash-safe via the PR 4 tmp +
  ``os.replace`` manifest pattern: leaves are base64 RAW BYTES (bit
  exact, not JSON floats) under a SHA-256 self-checksum, the newest
  verified snapshot wins at restore, corrupt files are quarantined.

- :class:`TrackingPipeline` is the first stateful DAG on PR 18's
  compiled stages: a detector :class:`~.pipeline.ModelStage` runs every
  Kth frame; between detections a compiled ``advance`` program
  propagates the slate (constant-velocity + score decay); on detect
  frames a compiled ``update`` program associates fresh detections to
  the previous slate (nearest-center EMA). All three programs are
  AOT-compiled per (bucket, mesh) and cached by the engine's compile
  cache, and the slate never leaves the device on the frame path — the
  only host round-trips are the on-cadence snapshots (the JX128 lint
  contract).

- Honesty contract: every stateful response carries ``state_reset`` —
  False when the slate's lineage is intact (fresh stream, in-order
  frame, snapshot restore + replay), True when state was genuinely
  lost (no snapshot survives, or a sequence gap the replay window
  couldn't cover). Never a silent reset.

Chaos sites (``resilience/faults.py``): ``session_kill`` drops a
committed session's device state (snapshots kept) so the next frame
exercises restore in-process; ``snapshot_corrupt`` garbles the
just-written snapshot so restore must fall back or declare the reset.

This module imports jax lazily (method bodies only): the fleet parent
process (``serve.py --fleet``) stays jax-free.
"""

from __future__ import annotations

import base64
import hashlib
import itertools
import json
import os
import re
import threading
import time
from pathlib import Path

import numpy as np

from deepvision_tpu.serve.admission import ShedError

__all__ = [
    "SessionStore",
    "TrackingPipeline",
    "synthetic_detector",
    "SNAPSHOT_VERSION",
]

SNAPSHOT_VERSION = 1

_tmp_seq = itertools.count()

_SAFE_SID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")

# SessionStore counters, exported as ``session_<name>`` metrics
_COUNTERS = ("opened", "evicted_ttl", "shed_capacity", "snapshots",
             "restores", "resets", "snapshot_corrupt", "killed",
             "duplicates")


def _safe_sid(sid: str) -> str:
    """Filesystem-safe snapshot stem for a session id."""
    if _SAFE_SID_RE.match(sid):
        return sid
    return "h" + hashlib.sha1(sid.encode()).hexdigest()[:16]


class _Session:
    __slots__ = ("sid", "state", "seq", "opened_t", "last_used",
                 "snap_seq", "last_snap_t", "frames_since_snap")

    def __init__(self, sid: str, now: float):
        self.sid = sid
        self.state = None        # device pytree (flat {name: array}) or None
        self.seq = -1            # last APPLIED frame seq (-1: none)
        self.opened_t = now
        self.last_used = now
        self.snap_seq = -1       # seq covered by the newest committed snapshot
        self.last_snap_t = None  # wall-clock time of the newest snapshot
        self.frames_since_snap = 0


class _Frame:
    """Disposition of one (sid, seq) arrival — what the engine does
    with it. ``action`` is ``apply`` (run the DAG, commit state) or
    ``duplicate`` (seq already applied: a replayed/retried frame; answer
    idempotently without touching state)."""

    __slots__ = ("entry", "action", "reset", "restored", "run_detect")

    def __init__(self, entry, action, reset, restored, run_detect):
        self.entry = entry
        self.action = action
        self.reset = reset
        self.restored = restored
        self.run_detect = run_detect


class SessionStore:
    """Bounded, TTL-evicted table of per-session device state with
    crash-safe host snapshots.

    Concurrency: one lock guards the table; the engine's dispatcher is
    the only state writer (``begin_frame``/``commit``), ``admit`` runs
    on submitter threads, the TTL sweep piggybacks on both.
    """

    def __init__(self, *, capacity: int = 64, ttl_s: float = 300.0,
                 snapshot_dir: str | Path | None = None,
                 snapshot_every: int = 8, keep_snapshots: int = 2,
                 injector=None, registry=None):
        self._lock = threading.RLock()
        self._sessions: dict[str, _Session] = {}
        self.capacity = max(1, int(capacity))
        self.ttl_s = float(ttl_s)
        self.snapshot_dir = Path(snapshot_dir) if snapshot_dir else None
        if self.snapshot_dir is not None:
            self.snapshot_dir.mkdir(parents=True, exist_ok=True)
        self.snapshot_every = max(1, int(snapshot_every))
        self.keep_snapshots = max(1, int(keep_snapshots))
        self._injector = injector
        self._c = {k: 0 for k in _COUNTERS}
        self._registry = registry
        if registry is not None:
            for k in _COUNTERS:
                registry.counter(f"session_{k}")

    # -- internal ---------------------------------------------------------
    def _count(self, key: str, n: int = 1) -> None:
        self._c[key] += n
        if self._registry is not None:
            self._registry.counter(f"session_{key}").inc(n)

    def _now(self) -> float:
        return time.monotonic()

    def _evict_expired_locked(self) -> list[tuple]:
        """Drop expired sessions; returns snapshot-capture tasks for
        the dirty ones. The CALLER writes them after releasing the lock
        (snapshot file I/O never runs under ``_lock`` — a slow disk
        must not stall every other stream's frame)."""
        if self.ttl_s <= 0:
            return []
        now = self._now()
        tasks = []
        dead = [sid for sid, e in self._sessions.items()
                if now - e.last_used > self.ttl_s]
        for sid in dead:
            # final snapshot so an evicted-then-resumed stream restores
            # instead of resetting (snapshots also outlive eviction)
            e = self._sessions[sid]
            if e.state is not None and e.seq > e.snap_seq:
                task = self._capture_locked(e)
                if task is not None:
                    tasks.append(task)
            del self._sessions[sid]
            self._count("evicted_ttl")
        return tasks

    # -- admission (engine.submit path) -----------------------------------
    def admit(self, sid: str) -> None:
        """Open or touch a session at submit time. Sheds NEW sessions
        when the table is full — existing state is never dropped to
        make room (that would be a silent reset)."""
        tasks: list[tuple] = []
        try:
            with self._lock:
                tasks = self._evict_expired_locked()
                e = self._sessions.get(sid)
                if e is not None:
                    e.last_used = self._now()
                    return
                if len(self._sessions) >= self.capacity:
                    self._count("shed_capacity")
                    raise ShedError(
                        f"session capacity {self.capacity} reached; new "
                        f"session {sid!r} shed (existing streams keep "
                        "their state)",
                        retry_after_s=min(self.ttl_s, 5.0))
                self._sessions[sid] = _Session(sid, self._now())
                self._count("opened")
        finally:
            # eviction snapshots land even on the shed path
            for task in tasks:
                self._write_snapshot(*task)

    # -- frame protocol (dispatcher path) ---------------------------------
    def begin_frame(self, sid: str, seq: int, detect_every: int) -> _Frame:
        """Disposition for one arriving frame. Restores from the newest
        verified snapshot when device state is missing; declares (never
        hides) a reset when lineage cannot be recovered."""
        with self._lock:
            e = self._sessions.get(sid)
            if e is None:
                # post-migration arrival without a fresh admit (the
                # router replays straight into the new replica)
                e = self._sessions[sid] = _Session(sid, self._now())
                self._count("opened")
            e.last_used = self._now()
            restored = False
            if e.state is None and e.seq < 0:
                restored = self._restore_locked(e)
            if seq <= e.seq:
                self._count("duplicates")
                return _Frame(e, "duplicate", False, restored, False)
            reset = False
            if e.seq < 0:
                # no recoverable lineage: seq 0 is a legitimate fresh
                # start; anything later means frames were lost
                reset = seq > 0
            elif seq != e.seq + 1:
                # sequence gap the replay window didn't cover
                reset = True
            if reset:
                e.state = None
                self._count("resets")
            run_detect = (e.state is None) or (seq % detect_every == 0)
            return _Frame(e, "apply", reset, restored, run_detect)

    def commit(self, sid: str, seq: int, state_row) -> None:
        """Commit one applied frame's new device state. Runs the
        snapshot cadence and the ``session_kill`` chaos site."""
        task = None
        with self._lock:
            e = self._sessions.get(sid)
            if e is None:  # evicted mid-flight; drop silently
                return
            e.state = state_row
            e.seq = seq
            e.last_used = self._now()
            e.frames_since_snap += 1
            inj = self._injector
            if inj is not None and inj.check_session_kill():
                # device state lost (as if the owning process died);
                # snapshots survive, so the next frame restores
                e.state = None
                e.seq = -1
                e.frames_since_snap = 0
                self._count("killed")
                print(f"[fault] dropped session {sid} device state "
                      f"(seq {seq})", flush=True)
                return
            if (self.snapshot_dir is not None
                    and e.frames_since_snap >= self.snapshot_every):
                task = self._capture_locked(e)
        if task is not None:  # file I/O outside the lock
            self._write_snapshot(*task)

    # -- snapshots --------------------------------------------------------
    def _snap_path(self, sid: str, seq: int) -> Path:
        return self.snapshot_dir / f"{_safe_sid(sid)}-{seq:012d}.snap.json"

    def _capture_locked(self, e: _Session) -> tuple | None:
        """Capture ``(sid, seq, state)`` for a snapshot and update the
        cadence bookkeeping — runs UNDER the store lock, touches no
        files. The captured state reference stays internally consistent
        even if a later commit swaps ``e.state`` before the write
        lands."""
        if e.state is None or self.snapshot_dir is None:
            return None
        e.snap_seq = e.seq
        e.last_snap_t = time.time()
        e.frames_since_snap = 0
        return (e.sid, e.seq, e.state)

    def _write_snapshot(self, sid: str, seq: int, state) -> None:
        """Encode + atomically write one captured snapshot — runs
        OUTSIDE the store lock (device fetch and file I/O must not
        stall other streams' frames)."""
        import jax

        host = jax.device_get(state)  # the ONE on-cadence host sync
        leaves = {}
        for name in sorted(host):
            arr = np.asarray(host[name])
            leaves[name] = {
                "b64": base64.b64encode(arr.tobytes()).decode("ascii"),
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
            }
        body = {"version": SNAPSHOT_VERSION, "sid": sid, "seq": seq,
                "leaves": leaves}
        payload = json.dumps(body, sort_keys=True).encode()
        doc = dict(body)
        doc["sha256"] = hashlib.sha256(payload).hexdigest()
        target = self._snap_path(sid, seq)
        # PR 4 manifest pattern: unique tmp, one atomic os.replace
        tmp = target.with_suffix(
            f".json.tmp.{os.getpid()}.{next(_tmp_seq)}")
        tmp.write_text(json.dumps(doc, sort_keys=True))
        os.replace(tmp, target)
        self._count("snapshots")
        if self._injector is not None:
            self._injector.corrupt_snapshot(target)
        self._prune_snapshots(sid)

    def _prune_snapshots(self, sid: str) -> None:
        snaps = sorted(self.snapshot_dir.glob(f"{_safe_sid(sid)}-*.snap.json"))
        for old in snaps[:-self.keep_snapshots]:
            try:
                old.unlink()
            except OSError:
                pass

    @staticmethod
    def verify_snapshot(path: Path) -> tuple[bool, str]:
        """(ok, reason) for one snapshot file — checksum + structure."""
        try:
            doc = json.loads(Path(path).read_text())
        except (OSError, ValueError) as exc:
            return False, f"unreadable: {exc}"
        want = doc.pop("sha256", None) if isinstance(doc, dict) else None
        if want is None:
            return False, "missing sha256"
        payload = json.dumps(doc, sort_keys=True).encode()
        got = hashlib.sha256(payload).hexdigest()
        if got != want:
            return False, f"checksum mismatch {got[:12]} != {want[:12]}"
        if doc.get("version") != SNAPSHOT_VERSION:
            return False, f"version {doc.get('version')}"
        return True, "ok"

    @staticmethod
    def load_snapshot(path: Path) -> tuple[int, dict]:
        """Decode a VERIFIED snapshot into (seq, host pytree). Raw-byte
        b64 leaves: the round trip is bit-exact."""
        doc = json.loads(Path(path).read_text())
        state = {}
        for name, leaf in doc["leaves"].items():
            buf = base64.b64decode(leaf["b64"])
            state[name] = np.frombuffer(
                buf, dtype=np.dtype(leaf["dtype"])).reshape(leaf["shape"])
        return int(doc["seq"]), state

    def _restore_locked(self, e: _Session) -> bool:
        if self.snapshot_dir is None:
            return False
        snaps = sorted(
            self.snapshot_dir.glob(f"{_safe_sid(e.sid)}-*.snap.json"),
            reverse=True)  # newest first (seq is zero-padded)
        for path in snaps:
            ok, reason = self.verify_snapshot(path)
            if not ok:
                self._count("snapshot_corrupt")
                print(f"[sessions] quarantined corrupt snapshot {path}: "
                      f"{reason}", flush=True)
                try:
                    os.replace(path, path.with_suffix(".json.corrupt"))
                except OSError:
                    pass
                continue
            seq, host = self.load_snapshot(path)
            # host leaves, not a bare device_put: the store knows no
            # mesh. The next frame's batch stack places the row with
            # the batch's sharding, and that frame's commit swaps in
            # the compiled program's device rows.
            e.state = host
            e.seq = seq
            e.snap_seq = seq
            e.frames_since_snap = 0
            self._count("restores")
            return True
        return False

    def flush(self) -> int:
        """Snapshot every session with un-snapshotted state (graceful
        close). Returns the number of snapshots written."""
        tasks = []
        with self._lock:
            if self.snapshot_dir is None:
                return 0
            for e in self._sessions.values():
                if e.state is not None and e.seq > e.snap_seq:
                    task = self._capture_locked(e)
                    if task is not None:
                        tasks.append(task)
        for task in tasks:  # file I/O outside the lock
            self._write_snapshot(*task)
        return len(tasks)

    def abandon(self) -> None:
        """Drop all device state WITHOUT flushing — crash semantics for
        in-process replica kills, so restore runs off the cadence
        snapshots exactly as it would after a real SIGKILL."""
        with self._lock:
            for e in self._sessions.values():
                e.state = None
                e.seq = -1
            self._sessions.clear()

    # -- introspection ----------------------------------------------------
    def pinned_bytes(self) -> int:
        """Device bytes pinned by live session state — pure aval math,
        no host sync."""
        with self._lock:
            total = 0
            for e in self._sessions.values():
                if e.state is None:
                    continue
                for leaf in e.state.values():
                    total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            return total

    def snapshot_age_s(self) -> float | None:
        """Age of the STALEST live session's newest snapshot (worst-case
        replay distance), None when nothing has been snapshotted."""
        with self._lock:
            ages = [time.time() - e.last_snap_t
                    for e in self._sessions.values()
                    if e.last_snap_t is not None]
            return max(ages) if ages else None

    def stats(self) -> dict:
        with self._lock:
            tasks = self._evict_expired_locked()
            age = self.snapshot_age_s()
            out = {
                "live": len(self._sessions),
                "capacity": self.capacity,
                "ttl_s": self.ttl_s,
                "pinned_bytes": self.pinned_bytes(),
                "snapshot_age_s": (round(age, 3)
                                   if age is not None else None),
                "snapshot_every": self.snapshot_every,
                "counters": dict(self._c),
            }
        for task in tasks:  # eviction snapshots, outside the lock
            self._write_snapshot(*task)
        return out


# ------------------------------------------------------- track-slate math
#
# The slate is a fixed-shape flat pytree per stream — ``slots`` tracks:
#   boxes    (slots, 4) f32   normalized corner boxes (x1, y1, x2, y2)
#   velocity (slots, 4) f32   per-frame corner deltas
#   scores   (slots,)   f32   confidence; <= 0 means an empty slot
#   age      (slots,)   f32   frames since the track was (re)acquired
#
# Everything below is pure jnp over a leading batch dim, position
# independent per row — the determinism pin the chaos drill gates on:
# the same frames produce bit-identical slates regardless of which
# replica, batch position, or restore path computed them.

def slate_spec(slots: int) -> dict:
    """{name: (shape, dtype)} for one stream's slate (no batch dim)."""
    return {
        "boxes": ((slots, 4), np.float32),
        "velocity": ((slots, 4), np.float32),
        "scores": ((slots,), np.float32),
        "age": ((slots,), np.float32),
    }


def _zero_slate(slots: int, batch: int):
    import jax.numpy as jnp

    return {name: jnp.zeros((batch, *shape), dtype)
            for name, (shape, dtype) in slate_spec(slots).items()}


def _centers(boxes):
    # (..., 4) corner boxes -> (..., 2) centers
    return 0.5 * (boxes[..., :2] + boxes[..., 2:])


def _track_update(slates, det, *, slots: int, ema: float):
    """Detect-frame program: select the top ``slots`` detections and
    associate them to the previous slate by nearest center (EMA blend,
    per-frame velocity). Batched over the leading dim; fixed shapes."""
    import jax
    import jax.numpy as jnp

    scores = jnp.where(det["valid"], det["scores"], -1.0)
    sel_scores, sel_idx = jax.lax.top_k(scores, slots)       # (B, slots)
    sel_boxes = jnp.take_along_axis(
        det["boxes"], sel_idx[..., None], axis=1)            # (B, slots, 4)
    det_valid = sel_scores > 0.0

    prev_boxes = slates["boxes"]
    prev_valid = slates["scores"] > 0.0
    # (B, prev, new) center distances, invalid prev slots pushed to +inf
    dist = jnp.linalg.norm(
        _centers(prev_boxes)[:, :, None, :]
        - _centers(sel_boxes)[:, None, :, :], axis=-1)
    dist = jnp.where(prev_valid[:, :, None], dist, jnp.inf)
    match = jnp.argmin(dist, axis=1)                         # (B, new)
    has_match = (jnp.isfinite(jnp.min(dist, axis=1)) & det_valid)
    m_boxes = jnp.take_along_axis(prev_boxes, match[..., None], axis=1)
    m_age = jnp.take_along_axis(slates["age"], match, axis=1)

    blend = ema * sel_boxes + (1.0 - ema) * m_boxes
    new_boxes = jnp.where(has_match[..., None], blend, sel_boxes)
    velocity = jnp.where(has_match[..., None], new_boxes - m_boxes, 0.0)
    new_scores = jnp.maximum(sel_scores, 0.0)
    age = jnp.where(has_match, m_age + 1.0, 0.0)

    new_slates = {"boxes": new_boxes, "velocity": velocity,
                  "scores": new_scores, "age": age}
    out = {"boxes": new_boxes, "scores": new_scores,
           "tracked": new_scores > 0.0}
    return new_slates, out


def _track_advance(slates, *, damp: float, decay: float):
    """Interpolation-frame program: constant-velocity propagation with
    velocity damping and score decay. No detector, no host traffic."""
    boxes = slates["boxes"] + slates["velocity"]
    new_slates = {
        "boxes": boxes,
        "velocity": slates["velocity"] * damp,
        "scores": slates["scores"] * decay,
        "age": slates["age"] + 1.0,
    }
    out = {"boxes": boxes, "scores": new_slates["scores"],
           "tracked": new_slates["scores"] > 0.0}
    return new_slates, out


class _TrackRunner:
    """Per-(bucket, mesh) compiled programs for one TrackingPipeline:
    ``detect`` (the stage forward), ``update`` (associate), ``advance``
    (interpolate). Calling the runner directly runs the detect path on
    a zero slate — that is what ``engine.warm()`` zero-executes."""

    __slots__ = ("detect", "update", "advance", "bucket", "slots")

    def __init__(self, detect, update, advance, bucket, slots):
        self.detect = detect
        self.update = update
        self.advance = advance
        self.bucket = bucket
        self.slots = slots

    def zero_slates(self):
        return _zero_slate(self.slots, self.bucket)

    def __call__(self, xd):
        _, out = self.update(self.zero_slates(), self.detect(xd))
        return out


class TrackingPipeline:
    """Tracking-by-detection as a stateful DAG on PR 18's stages.

    Wraps a detect-task :class:`~.models.ServedModel`: the detector
    stage runs every ``detect_every``-th frame of each stream (and on
    any frame where the stream has no state yet); frames in between run
    the compiled ``advance`` program only. The per-stream slate lives
    in ``store`` (a :class:`SessionStore`), threaded through the
    engine's existing admission/deadline path via ``session``/``seq``
    on submit.

    Duck-types the ServedModel surface the engine consumes (``name``,
    ``input_shape``, ``dtype_str``, ``buckets``, ``compile_for``,
    ``postprocess``) plus ``is_stateful = True`` which routes dispatch
    to the stateful batch path.
    """

    is_pipeline = False
    is_stateful = True
    task = "track"
    precompiled = None
    scale = "unit"

    def __init__(self, name: str, detector, store: SessionStore, *,
                 detect_every: int = 4, slots: int = 4, ema: float = 0.5,
                 damp: float = 0.9, decay: float = 0.9):
        from deepvision_tpu.serve.pipeline import PipelineError

        self.name = name
        self.detector = detector
        self.store = store
        self.detect_every = max(1, int(detect_every))
        self.slots = int(slots)
        self.ema = float(ema)
        self.damp = float(damp)
        self.decay = float(decay)
        self._stage = detector.as_stage()
        if getattr(detector, "task", None) != "detect":
            raise PipelineError(
                f"TrackingPipeline {name!r} needs a detect-task model, "
                f"got task {getattr(detector, 'task', None)!r}")

    # -- ServedModel surface ----------------------------------------------
    @property
    def input_shape(self):
        return self.detector.input_shape

    @property
    def input_dtype(self):
        return self.detector.input_dtype

    @property
    def dtype_str(self) -> str:
        return self.detector.dtype_str

    @property
    def buckets(self):
        return self.detector.buckets

    @property
    def variables(self):
        return None

    def stage_models(self) -> dict:
        """The stage map the engine replicates variables for (same
        contract as Pipeline.stage_models)."""
        return {"detector": self._stage}

    def compile_for(self, bucket: int, mesh) -> _TrackRunner:
        """AOT-compile detect/update/advance at ``bucket`` and validate
        the detector's output contract via its avals (no FLOPs)."""
        import functools

        import jax

        from deepvision_tpu.serve.pipeline import PipelineError

        out = self._stage.out_avals(bucket)
        need = ("boxes", "scores", "valid")
        if not isinstance(out, dict) or any(k not in out for k in need):
            have = sorted(out) if isinstance(out, dict) else type(out)
            raise PipelineError(
                f"tracking detector {self._stage.name!r} must emit a "
                f"detect-style dict with keys {need}, got {have}")
        detect = self._stage.compile(bucket, mesh, donate=True)
        slate_avals = {
            name: jax.ShapeDtypeStruct((bucket, *shape), dtype)
            for name, (shape, dtype) in slate_spec(self.slots).items()}
        det_avals = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for k, v in out.items()}
        upd = functools.partial(_track_update, slots=self.slots,
                                ema=self.ema)
        adv = functools.partial(_track_advance, damp=self.damp,
                                decay=self.decay)
        update = jax.jit(upd).lower(slate_avals, det_avals).compile()
        advance = jax.jit(adv).lower(slate_avals).compile()
        return _TrackRunner(detect, update, advance, bucket, self.slots)

    def postprocess(self, host: dict, i: int) -> dict:
        """Per-row result from the fetched batch output. Deterministic
        fields only — the engine merges session/seq/state_reset in."""
        return {
            "boxes": np.asarray(host["boxes"][i]).tolist(),
            "scores": np.asarray(host["scores"][i]).tolist(),
            "tracked": np.asarray(host["tracked"][i]).astype(bool).tolist(),
        }


# ------------------------------------------------- synthetic detector

def synthetic_detector(name: str = "synth", size: int = 16,
                       channels: int = 1, candidates: int = 8):
    """A weight-free detect-task ServedModel for stream drills: boxes
    derive from per-quadrant image moments — device-computed, fully
    deterministic, compiles in milliseconds. The chaos drill's
    determinism pin (fault run outputs == fault-free twin) leans on
    this plus the bit-exact snapshot round trip."""
    from deepvision_tpu.serve.models import ServedModel

    def forward(variables, x):
        import jax.numpy as jnp

        b = x.shape[0]
        # quadrant means -> candidate box geometry; any fixed pure
        # function of the frame works, moments keep it smooth
        flat = x.reshape(b, -1)
        n = flat.shape[1]
        k = candidates
        chunk = max(1, n // k)
        means = jnp.stack(
            [flat[:, i * chunk:(i + 1) * chunk].mean(axis=1)
             for i in range(k)], axis=1)                     # (B, k)
        frac = (jnp.tanh(means) + 1.0) * 0.5                 # (0, 1)
        idx = jnp.arange(k, dtype=jnp.float32) / k
        x1 = jnp.clip(frac * 0.5 + idx[None, :] * 0.25, 0.0, 0.9)
        y1 = jnp.clip(frac * 0.25 + idx[None, :] * 0.5, 0.0, 0.9)
        wh = 0.05 + frac * 0.1
        boxes = jnp.stack(
            [x1, y1, jnp.clip(x1 + wh, 0.0, 1.0),
             jnp.clip(y1 + wh, 0.0, 1.0)], axis=-1)          # (B, k, 4)
        scores = 0.2 + 0.8 * frac
        return {"boxes": boxes, "scores": scores,
                "classes": jnp.zeros_like(scores, dtype=jnp.int32),
                "valid": scores > 0.25}

    def post(host, i):
        keep = np.asarray(host["valid"][i]).astype(bool)
        return {"boxes": np.asarray(host["boxes"][i])[keep].tolist(),
                "scores": np.asarray(host["scores"][i])[keep].tolist()}

    return ServedModel(name=name, task="detect", forward=forward,
                       variables={}, input_shape=(size, size, channels),
                       postprocess=post)
