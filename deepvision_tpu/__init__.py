"""deepvision_tpu — a TPU-native (JAX/XLA/Pallas/pjit) deep-vision framework.

A ground-up re-design of the capabilities of `dotdotdotcg/deep-vision`
(an educational CV model zoo: classification / detection / pose / GANs)
as ONE shared library instead of per-model copy-paste:

- ``core``     : mesh + sharding setup, precision policy, PRNG discipline,
                 train-step compilation (jit/pjit with donated args).
- ``data``     : host-side input pipelines (tf.data + pure-python TFRecord
                 codec), dataset builders, augmentation library.
- ``models``   : Flax modules for every reference network family.
- ``ops``      : jit-able tensor ops (IoU, NMS, LRN, label encoders) and
                 Pallas TPU kernels for the hot spots.
- ``losses``   : pure-function losses (CE/top-k, YOLO multiscale, heatmap
                 MSE, GAN losses).
- ``train``    : Trainer + GAN loop, optimizers, LR schedules,
                 checkpointing (Orbax), metric loggers, GCS publication.
- ``parallel`` : explicit-collective patterns (shard_map + ppermute ring
                 halo exchange for spatial partitioning); the default
                 GSPMD path lives in ``core`` (mesh/shardings, ZeRO-1
                 weight-update sharding) and ``data.device_put``
                 (multi-host batch placement).
- ``convert``  : PyTorch/TF checkpoint import + layer-for-layer activation
                 diffing + hash-verified pretrained ingestion.
- ``eval``     : offline metrics (detection mAP, pose PCK) the reference
                 never shipped.
- ``serve``    : batched inference runtime (bucketed AOT executable
                 cache, admission control, serving telemetry) behind the
                 ``serve.py`` stdin-JSONL/HTTP CLI.
- ``resilience``: deterministic fault injection + bounded recovery
                 (NaN-rollback, checkpoint integrity manifests with
                 quarantine/fallback, transient-read retries, supervised
                 serve dispatcher).

Reference behavior is cited throughout as ``ref: <file:line>`` meaning a
path under the upstream `deep-vision` repo.
"""

__version__ = "0.1.0"
