"""Model export: serialized StableHLO artifacts (jax.export).

The TPU-native analog of the reference's deployment surface — CycleGAN's
saved_model + TFLite converter (ref: CycleGAN/tensorflow/convert.py:7-14,
inference.py:26-72): the jitted forward function is lowered once and
serialized with its input signature; the artifact reloads and executes
without the model's Python code.
"""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export


def export_forward(apply_fn, variables, sample_input, *, train_kwarg=True):
    """Lower ``apply_fn(variables, x, train=False)`` at the sample's
    shape/dtype and return the serialized bytes."""

    def forward(x):
        if train_kwarg:
            return apply_fn(variables, x, train=False)
        return apply_fn(variables, x)

    spec = jax.ShapeDtypeStruct(
        np.shape(sample_input), jnp.asarray(sample_input).dtype
    )
    exported = jax_export.export(jax.jit(forward))(spec)
    return exported.serialize()


def export_callable(fn, in_avals) -> bytes:
    """Lower an arbitrary jit-able callable at explicit input avals and
    return the serialized StableHLO bytes. The general form of
    :func:`export_forward` — the serve artifact store uses it to
    persist a ``ServedModel``'s whole request program (forward +
    in-graph post-processing, weights baked in as constants) keyed by
    compile-cache bucket."""
    exported = jax_export.export(jax.jit(fn))(*in_avals)
    return exported.serialize()


def deserialize_exported(data: bytes):
    """StableHLO bytes -> callable — the in-memory dual of
    :func:`load_exported` for callers that manage their own files and
    integrity manifests (``serve.artifact_store``). The callable
    carries the same ``.in_avals`` / ``.out_avals`` / ``.exported``
    metadata contract."""
    exported = jax_export.deserialize(data)

    def call(*args):
        return exported.call(*args)

    call.in_avals = exported.in_avals
    call.out_avals = exported.out_avals
    call.exported = exported
    return call


def save_exported(path: str | Path, data: bytes) -> Path:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(data)
    return path


def load_exported(path: str | Path):
    """-> callable(x) running the deserialized computation.

    The callable carries the artifact's FULL signature as metadata —
    ``.in_avals`` / ``.out_avals`` (the ``ShapeDtypeStruct`` tuples the
    forward was lowered at) and ``.exported`` (the raw
    ``jax.export.Exported``) — because a StableHLO artifact is
    shape-specialized: a serving host (``serve.models.from_stablehlo``)
    must know the exported batch size to pin its bucket ladder, a
    pipeline validator (``serve.pipeline``) must know the output
    shapes/dtypes to type-check a DAG edge BEFORE any compile, and a
    caller feeding the wrong shape should find out from the spec, not
    a runtime shape error."""
    return deserialize_exported(Path(path).read_bytes())
