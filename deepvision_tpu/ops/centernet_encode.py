"""CenterNet ground-truth encoding as a pure, vectorized jnp op.

The reference never finished this part (its heatmap generator returns
early — ref: ObjectsAsPoints/tensorflow/preprocess.py:129-138); this is
the completed capability, following the Objects-as-Points recipe the
reference cites: class-wise center heatmaps splatted with size-adaptive
Gaussians (CornerNet ``gaussian_radius``, min-overlap 0.7), box
width/height and sub-cell center offsets regressed at center cells.

TPU-first design: one fixed-shape ``.at[].max`` patch scatter per box
(patches clipped to ``max_radius``), run inside the jitted train step —
no host loops, no dynamic shapes (same design as ops/yolo_encode).
"""

from __future__ import annotations

import jax.numpy as jnp

MIN_OVERLAP = 0.7  # CornerNet radius IoU target
MAX_RADIUS = 6  # patch cap: (2·6+1)² scatter per box


def gaussian_radius(height, width, min_overlap: float = MIN_OVERLAP):
    """Largest corner displacement (in cells) keeping IoU ≥ min_overlap
    (the CornerNet formula: min of the three quadratic cases)."""
    a1 = 1.0
    b1 = height + width
    c1 = width * height * (1 - min_overlap) / (1 + min_overlap)
    sq1 = jnp.sqrt(jnp.maximum(b1 * b1 - 4 * a1 * c1, 0.0))
    r1 = (b1 - sq1) / (2 * a1)

    a2 = 4.0
    b2 = 2 * (height + width)
    c2 = (1 - min_overlap) * width * height
    sq2 = jnp.sqrt(jnp.maximum(b2 * b2 - 4 * a2 * c2, 0.0))
    r2 = (b2 - sq2) / (2 * a2)

    a3 = 4.0 * min_overlap
    b3 = -2 * min_overlap * (height + width)
    c3 = (min_overlap - 1) * width * height
    sq3 = jnp.sqrt(jnp.maximum(b3 * b3 - 4 * a3 * c3, 0.0))
    r3 = (b3 + sq3) / (2 * a3)
    return jnp.minimum(jnp.minimum(r1, r2), r3)


def encode_centernet(
    boxes_xywh: jnp.ndarray,
    labels: jnp.ndarray,
    num_classes: int,
    grid_size: int,
    *,
    max_radius: int = MAX_RADIUS,
) -> dict:
    """(B, M, 4) normalized xywh + (B, M) labels (−1 pad) → dense targets.

    Returns dict of
      heatmap: (B, G, G, C) Gaussian class heatmaps (peak 1, max-combined),
      wh:      (B, G, G, 2) box sizes in cells at center cells,
      offset:  (B, G, G, 2) sub-cell center offsets in [0, 1),
      mask:    (B, G, G) 1.0 at object centers.
    """
    B, M = labels.shape
    G = grid_size
    valid = labels >= 0  # (B, M)
    cls = jnp.clip(labels, 0, num_classes - 1)

    cx = boxes_xywh[..., 0] * G
    cy = boxes_xywh[..., 1] * G
    w = boxes_xywh[..., 2] * G
    h = boxes_xywh[..., 3] * G
    ix = jnp.clip(cx.astype(jnp.int32), 0, G - 1)  # (B, M)
    iy = jnp.clip(cy.astype(jnp.int32), 0, G - 1)

    radius = jnp.maximum(gaussian_radius(h, w), 0.0)
    sigma = jnp.maximum((2 * radius + 1) / 6.0, 1e-3)  # CornerNet diameter/6

    # Patch scatter: K×K window around each center, max-combined.
    K = 2 * max_radius + 1
    d = jnp.arange(K) - max_radius  # (K,)
    px = ix[..., None, None] + d[None, None, :, None]  # (B, M, K, 1)→x
    py = iy[..., None, None] + d[None, None, None, :]  # (B, M, 1, K)→y
    px = jnp.broadcast_to(px, (B, M, K, K))
    py = jnp.broadcast_to(py, (B, M, K, K))
    # Gaussians are centered on the integer center cell, as in the
    # canonical draw_umich_gaussian.
    fx = ix.astype(jnp.float32)[..., None, None]
    fy = iy.astype(jnp.float32)[..., None, None]
    d2 = (px - fx) ** 2 + (py - fy) ** 2
    g = jnp.exp(-d2 / (2.0 * sigma[..., None, None] ** 2))
    # zero out both padding boxes and cells beyond this box's own radius
    # (CornerNet draws only within the computed radius)
    rint = jnp.minimum(jnp.ceil(radius), float(max_radius))
    within = (jnp.abs(px - ix[..., None, None]) <= rint[..., None, None]) & (
        jnp.abs(py - iy[..., None, None]) <= rint[..., None, None]
    )
    g = jnp.where(within & valid[..., None, None], g, 0.0)

    batch_idx = jnp.broadcast_to(
        jnp.arange(B)[:, None, None, None], (B, M, K, K)
    )
    cls_idx = jnp.broadcast_to(cls[..., None, None], (B, M, K, K))
    heatmap = jnp.zeros((B, G, G, num_classes), jnp.float32)
    heatmap = heatmap.at[
        batch_idx.reshape(-1),
        py.reshape(-1).clip(0, G - 1),
        px.reshape(-1).clip(0, G - 1),
        cls_idx.reshape(-1),
    ].max(
        # clip-to-edge would smear out-of-bounds patch cells onto border
        # pixels; zero them instead (max with 0 is a no-op).
        jnp.where(
            (py >= 0) & (py < G) & (px >= 0) & (px < G), g, 0.0
        ).reshape(-1)
    )

    # Center-cell regression targets (last-writer-wins on collisions, the
    # same semantics as a host-side scatter). Padding boxes scatter to an
    # out-of-bounds row and are DROPPED — they must not clobber cell (0,0).
    b_idx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, M)).reshape(-1)
    sy = jnp.where(valid, iy, G).reshape(-1)
    sx = ix.reshape(-1)
    wh = jnp.zeros((B, G, G, 2), jnp.float32)
    wh = wh.at[b_idx, sy, sx, :].set(
        jnp.stack([w, h], -1).reshape(-1, 2), mode="drop"
    )
    offset = jnp.zeros((B, G, G, 2), jnp.float32)
    offset = offset.at[b_idx, sy, sx, :].set(
        jnp.stack([cx - ix, cy - iy], -1).reshape(-1, 2), mode="drop"
    )
    mask = jnp.zeros((B, G, G), jnp.float32)
    mask = mask.at[b_idx, sy, sx].set(1.0, mode="drop")
    return {"heatmap": heatmap, "wh": wh, "offset": offset, "mask": mask}
