"""Local Response Normalization (cross-channel), the AlexNet-era op.

The PT reference uses ``nn.LocalResponseNorm`` (ref:
AlexNet/pytorch/models/alexnet_v1.py LRN layers); the TF reference hand-rolls
a Keras layer over ``tf.nn.local_response_normalization`` (ref:
AlexNet/tensorflow/models/alexnet_v2.py:9-24). JAX has no built-in, so this
is written as a windowed reduction over the channel axis — XLA fuses the
square/add/pow chain into one elementwise kernel around the reduce-window,
which is the right TPU lowering for this (rare, bandwidth-bound) op.

Semantics match torch: ``b_c = a_c / (k + (alpha/n) * sum_{c'} a_{c'}^2)^beta``
with the sum over a window of ``n`` channels centered at ``c``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def local_response_norm(
    x: jax.Array,
    size: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    k: float = 2.0,
    impl: str | None = None,
) -> jax.Array:
    """NHWC input; normalizes over the trailing channel axis.

    On a single-device TPU backend this dispatches to the fused Pallas
    kernel (ops/lrn_pallas.py — one VMEM-resident pass instead of XLA's
    reduce_window + elementwise chain). Multi-device stays on the jnp
    lowering: a ``pallas_call`` has no GSPMD partitioning rule, so under
    a sharded jit it would force a gather. ``impl`` overrides the
    dispatch ("jnp" | "pallas"); both paths are parity-pinned by
    tests/test_ops.py.
    """
    if impl is None:
        impl = (
            "pallas"
            if jax.default_backend() == "tpu" and jax.device_count() == 1
            else "jnp"
        )
    if impl == "pallas":
        from deepvision_tpu.ops.lrn_pallas import local_response_norm_pallas

        return local_response_norm_pallas(x, size, alpha, beta, k)
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    sq = x32 * x32
    half = size // 2
    window = [1] * (x.ndim - 1) + [size]
    sums = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=window,
        window_strides=[1] * x.ndim,
        padding=[(0, 0)] * (x.ndim - 1) + [(half, size - 1 - half)],
    )
    denom = jnp.power(k + (alpha / size) * sums, beta)
    return (x32 / denom).astype(dtype)
