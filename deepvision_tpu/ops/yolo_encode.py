"""YOLO v3 ground-truth grid encoder — fixed-shape, on-device.

The reference encodes labels on the host with ``TensorArray`` dynamic loops
and ``tensor_scatter_nd_update`` per image (ref:
YOLO/tensorflow/preprocess.py:137-269). TPU-first re-expression: the encoder
is a pure jnp function over PADDED boxes (B, MAX_BOXES, 4+1) that runs
INSIDE the jitted train step — one vectorized scatter per scale, padded
entries dropped via out-of-bounds indices (XLA scatter drop semantics).

Semantics parity:
- best-anchor assignment by centered wh-IoU against all 9 anchors
  (ref: preprocess.py:226-269),
- anchors normalized by 416 (ref: yolov3.py:18-20),
- grid y_true layout (x, y, w, h, obj, one-hot classes) with xywh relative
  to the full image (ref: preprocess.py:137-224).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# (w, h) / 416 — ref: yolov3.py:18-20
ANCHORS_WH = (
    np.array(
        [[10, 13], [16, 30], [33, 23], [30, 61], [62, 45], [59, 119],
         [116, 90], [156, 198], [373, 326]],
        np.float32,
    )
    / 416.0
)
GRID_SIZES = (52, 26, 13)  # scale 0 = small boxes ... 2 = large
MAX_BOXES = 100  # true-box cap (ref: yolov3.py:448-454)


def best_anchor(wh):
    """wh (..., 2) normalized -> best of the 9 anchors by centered IoU."""
    anchors = jnp.asarray(ANCHORS_WH)
    inter = jnp.minimum(wh[..., None, 0], anchors[:, 0]) * jnp.minimum(
        wh[..., None, 1], anchors[:, 1]
    )
    union = (
        wh[..., None, 0] * wh[..., None, 1]
        + anchors[:, 0] * anchors[:, 1]
        - inter
    )
    return jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)


def encode_labels(boxes, labels, num_classes: int, *,
                  grid_sizes=GRID_SIZES):
    """boxes (B, M, 4) xywh normalized to [0,1]; labels (B, M) int32 with
    -1 for padding -> tuple of 3 grids, each
    (B, S, S, 3, 5 + num_classes) in the dtype boxes promote to with
    f32 (f32 in training, f64 under the x64 parity tests).
    """
    b, m, _ = boxes.shape
    anchor_idx = best_anchor(boxes[..., 2:4])  # (B, M) in [0, 9)
    scale_idx = anchor_idx // 3
    within = anchor_idx % 3
    valid = labels >= 0

    batch_idx = jnp.broadcast_to(jnp.arange(b)[:, None], (b, m))
    onehot = jax.nn.one_hot(jnp.maximum(labels, 0), num_classes)
    features = jnp.concatenate(
        [boxes, jnp.ones((b, m, 1), boxes.dtype), onehot], axis=-1
    )  # (B, M, 5 + C)

    outputs = []
    for s, size in enumerate(grid_sizes):
        cell_x = jnp.floor(boxes[..., 0] * size).astype(jnp.int32)
        cell_y = jnp.floor(boxes[..., 1] * size).astype(jnp.int32)
        cell_x = jnp.clip(cell_x, 0, size - 1)
        cell_y = jnp.clip(cell_y, 0, size - 1)
        on_scale = valid & (scale_idx == s)
        # invalid rows scatter out of bounds -> dropped by XLA
        oob = jnp.where(on_scale, 0, size + 1)
        # match the boxes' dtype: f32 in training, f64 under the spatial
        # parity tests (a f32 grid there forces a lossy scatter cast that
        # newer JAX promotes to an error)
        grid = jnp.zeros((b, size, size, 3, features.shape[-1]),
                         features.dtype)
        grid = grid.at[
            batch_idx, cell_y + oob, cell_x, within
        ].set(features, mode="drop")
        outputs.append(grid)
    return tuple(outputs)
