"""CenterNet peak decoding: heatmaps → top-K detections (pure jnp).

The Objects-as-Points inference path the reference never reached: NMS is
a 3×3 max-pool peak test on the class heatmaps (no IoU suppression
needed), then top-K extraction with wh/offset gathered at the peak cells.
Fixed shapes throughout — jit/TPU friendly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def decode_centernet(
    heatmap_logits: jnp.ndarray,
    wh: jnp.ndarray,
    offset: jnp.ndarray,
    *,
    top_k: int = 100,
) -> dict:
    """(B, G, G, C) logits + (B, G, G, 2) wh/offset → top-K boxes.

    Returns dict of boxes (B, K, 4) normalized xywh, scores (B, K),
    classes (B, K) int32 — ordered by descending score.
    """
    B, G, _, C = heatmap_logits.shape
    scores = jax.nn.sigmoid(heatmap_logits.astype(jnp.float32))
    # 3x3 max-pool peak NMS: keep only local maxima.
    pooled = jax.lax.reduce_window(
        scores, -jnp.inf, jax.lax.max,
        (1, 3, 3, 1), (1, 1, 1, 1), "SAME",
    )
    scores = jnp.where(scores == pooled, scores, 0.0)

    flat = scores.reshape(B, -1)  # (B, G·G·C)
    top_scores, idx = jax.lax.top_k(flat, top_k)
    cls = (idx % C).astype(jnp.int32)
    cell = idx // C
    cy = cell // G
    cx = cell % G

    b = jnp.arange(B)[:, None]
    off = offset[b, cy, cx]  # (B, K, 2) = (dx, dy)
    sizes = wh[b, cy, cx]  # (B, K, 2) = (w, h) in cells
    x = (cx.astype(jnp.float32) + off[..., 0]) / G
    y = (cy.astype(jnp.float32) + off[..., 1]) / G
    boxes = jnp.stack(
        [x, y, sizes[..., 0] / G, sizes[..., 1] / G], axis=-1
    )
    return {"boxes": boxes, "scores": top_scores, "classes": cls}
