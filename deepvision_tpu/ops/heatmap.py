"""Gaussian keypoint-heatmap targets as a pure, vectorized jnp op.

Capability parity with ref: Hourglass/tensorflow/preprocess.py:91-173 —
per-joint 2-D Gaussians (σ=1, truncated to a 7×7 patch; all-zero map for
invisible or fully out-of-bounds joints). The reference builds each patch
with nested Python ``tf.TensorArray`` scatter loops per joint on the host;
here the whole (H, W, K) target is one broadcasted expression that runs
inside the jitted train step, so targets never cross the host↔device
boundary (same design as ops/yolo_encode).

Note the reference's Gaussian peak is 12, not 1: its
``generate_2d_guassian`` multiplies by a default ``scale=12``
(preprocess.py:91,120) despite the in-code comment saying the center
"should be 1". The paper (Newell et al. 2016, following Tompson et al.)
uses peak 1. ``peak`` defaults to 1.0 here; pass 12.0 for bit-parity with
the reference's targets.
"""

from __future__ import annotations

import jax.numpy as jnp


def gaussian_heatmaps(
    kx: jnp.ndarray,
    ky: jnp.ndarray,
    visible: jnp.ndarray,
    *,
    height: int = 64,
    width: int = 64,
    sigma: float = 1.0,
    peak: float = 1.0,
) -> jnp.ndarray:
    """(..., K) normalized keypoints -> (..., H, W, K) heatmaps.

    kx, ky: float in [0, 1] (fractions of heatmap width/height);
    visible: int/bool, 0 = occluded/absent -> all-zero map (ref
    preprocess.py:109: "a ground truth heatmap of all zeros is provided").
    Leading batch dimensions broadcast.
    """
    kx = jnp.asarray(kx, jnp.float32)
    ky = jnp.asarray(ky, jnp.float32)
    # Ref rounds to integer heatmap cells (preprocess.py:160-161); keep that
    # so targets match (and stay symmetric around the drawn center).
    x0 = jnp.round(kx * width)
    y0 = jnp.round(ky * height)

    xs = jnp.arange(width, dtype=jnp.float32)
    ys = jnp.arange(height, dtype=jnp.float32)
    # dx: (..., 1, W, K); dy: (..., H, 1, K)
    dx = xs[:, None] - x0[..., None, :]
    dy = ys[:, None] - y0[..., None, :]
    d2 = dx[..., None, :, :] ** 2 + dy[..., :, None, :] ** 2
    g = peak * jnp.exp(-d2 / (2.0 * sigma * sigma))

    # Truncate to the (6σ+1)² patch — exact zeros outside, like the ref's
    # patch scatter; a patch fully outside the map is then all zeros too.
    radius = 3.0 * sigma
    inside = (jnp.abs(dx[..., None, :, :]) <= radius) & (
        jnp.abs(dy[..., :, None, :]) <= radius
    )
    vis = (jnp.asarray(visible) > 0)[..., None, None, :]
    return jnp.where(inside & vis, g, 0.0).astype(jnp.float32)


def decode_heatmaps(heatmaps: jnp.ndarray):
    """(..., H, W, K) heatmaps -> per-joint argmax as ``(kx, ky, conf)``,
    each (..., K); kx/ky are normalized cell-center fractions of
    width/height (the inverse of :func:`gaussian_heatmaps`' encoding).

    The inference counterpart of the encoder above: a fixed-shape pure
    jnp reduction, so pose decoding runs INSIDE the compiled serving
    forward (serve/models.py) instead of as a host-side numpy
    ``unravel_index`` loop per image.
    """
    heatmaps = jnp.asarray(heatmaps, jnp.float32)
    h, w, k = heatmaps.shape[-3:]
    flat = heatmaps.reshape(*heatmaps.shape[:-3], h * w, k)
    idx = jnp.argmax(flat, axis=-2)
    conf = jnp.max(flat, axis=-2)
    ky = (idx // w).astype(jnp.float32) / h
    kx = (idx % w).astype(jnp.float32) / w
    return kx, ky, conf
