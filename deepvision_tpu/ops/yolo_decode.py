"""YOLO grid ↔ absolute box transforms (pure jnp, jit-able).

Semantics parity with ref: YOLO/tensorflow/yolov3.py:238-349:
- absolute: b_xy = (sigmoid(t_xy) + cell) / S, b_wh = exp(t_wh) * anchor,
  sigmoid objectness/classes,
- relative (inverse): t_xy = b_xy * S - cell, t_wh = log(b_wh / anchor)
  with non-finite entries (empty cells) zeroed.

Grids are (..., S, S, anchor, 5+C); cell coordinates are (x, y) with x the
W axis — axis -3 of the grid indexes rows (y), matching the reference's
meshgrid layout (ref: yolov3.py:263-291).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _cell_offsets(size: int):
    """(S, S, 1, 2) float32 where [y, x, 0] = (x, y)."""
    cx, cy = jnp.meshgrid(jnp.arange(size), jnp.arange(size))
    return jnp.stack([cx, cy], axis=-1)[:, :, None, :].astype(jnp.float32)


def decode_absolute(y_pred, anchors_wh, num_classes: int):
    """Raw grid (B, S, S, 3, 5+C) -> (boxes_xywh, objectness, classes).

    boxes are normalized to [0, 1] image coordinates; objectness (…, 1) and
    classes (…, C) are sigmoid probabilities (ref: yolov3.py:238-326).
    """
    size = y_pred.shape[-4]
    t_xy = y_pred[..., 0:2]
    t_wh = y_pred[..., 2:4]
    objectness = jax.nn.sigmoid(y_pred[..., 4:5])
    classes = jax.nn.sigmoid(y_pred[..., 5:])
    b_xy = (jax.nn.sigmoid(t_xy) + _cell_offsets(size)) / size
    b_wh = jnp.exp(t_wh) * jnp.asarray(anchors_wh, y_pred.dtype)
    return jnp.concatenate([b_xy, b_wh], axis=-1), objectness, classes


def encode_relative(true_xywh, anchors_wh):
    """Absolute grid targets (B, S, S, 3, 4) -> cell-relative (t_xy, t_wh).

    Inverse of :func:`decode_absolute` for loss computation
    (ref: yolov3.py:329-349). Cells without a box (wh=0) produce zeros.
    """
    size = true_xywh.shape[-4]
    t_xy = true_xywh[..., 0:2] * size - _cell_offsets(size)
    ratio = true_xywh[..., 2:4] / jnp.asarray(anchors_wh, true_xywh.dtype)
    t_wh = jnp.log(jnp.maximum(ratio, 1e-12))
    t_wh = jnp.where(ratio > 0, t_wh, 0.0)
    return jnp.concatenate([t_xy, t_wh], axis=-1)
