"""YOLO v3 inference postprocessing: decode all scales → batched NMS.

Behavior parity with ref: YOLO/tensorflow/postprocess.py:6-96 (concat the
three decoded scales, objectness-based score, greedy IoU suppression, max
100 detections) — but fixed-shape: the reference's per-image ``tf.map_fn``
with a dynamic while-loop becomes ops.nms.batched_nms (vmapped fori_loop),
so the whole path jit-compiles on TPU.
"""

from __future__ import annotations

import jax.numpy as jnp

from deepvision_tpu.ops.iou import xywh_to_corners
from deepvision_tpu.ops.nms import batched_nms
from deepvision_tpu.ops.yolo_decode import decode_absolute
from deepvision_tpu.ops.yolo_encode import ANCHORS_WH


def yolo_postprocess(
    pred_grids, num_classes: int, *,
    iou_thresh: float = 0.5, score_thresh: float = 0.5, max_out: int = 100,
):
    """Raw grids ((B,S,S,3,5+C) ×3) ->
    (boxes (B,K,4) corners, scores (B,K), classes (B,K), valid (B,K),
    n_candidates (B,) — NMS exactness tripwire, see ops.nms.nms_indices).

    Score = objectness (ref: postprocess.py:28-30); the reported class is
    the argmax class probability of the surviving box.
    """
    anchor_groups = (ANCHORS_WH[0:3], ANCHORS_WH[3:6], ANCHORS_WH[6:9])
    boxes, scores, classes = [], [], []
    for y_pred, anchors in zip(pred_grids, anchor_groups):
        b_xywh, obj, cls = decode_absolute(y_pred, anchors, num_classes)
        b = b_xywh.shape[0]
        boxes.append(xywh_to_corners(b_xywh).reshape(b, -1, 4))
        scores.append(obj.reshape(b, -1))
        classes.append(jnp.argmax(cls, axis=-1).reshape(b, -1))
    return batched_nms(
        jnp.concatenate(boxes, axis=1),
        jnp.concatenate(scores, axis=1),
        jnp.concatenate(classes, axis=1).astype(jnp.int32),
        iou_thresh=iou_thresh, score_thresh=score_thresh, max_out=max_out,
    )
