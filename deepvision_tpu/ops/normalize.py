"""On-device image normalization for uint8 wire transfer.

TPU-first bandwidth optimization: host pipelines may emit uint8 images
(4× less host↔device traffic than f32 — the link, not HBM, is the scarce
resource; data/imagenet.py ``as_uint8``); the compiled step then applies
the dataset family's normalization on device. Train steps call
:func:`maybe_normalize` so f32 batches (full preprocessing parity done on
the host) pass through untouched.
"""

from __future__ import annotations

import jax.numpy as jnp

IMAGENET_CHANNEL_MEANS = (123.68, 116.78, 103.94)  # ref: data_load.py:35-38
# torchvision ImageNet statistics — the PT reference's accuracy-canonical
# normalization (ref: ResNet/pytorch/train.py:322-324)
TORCH_CHANNEL_MEANS = (0.485, 0.456, 0.406)
TORCH_CHANNEL_STDS = (0.229, 0.224, 0.225)


def imagenet_normalize(images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] → f32 channel-mean-subtracted (classification nets)."""
    return images.astype(jnp.float32) - jnp.asarray(
        IMAGENET_CHANNEL_MEANS, jnp.float32
    )


def torch_normalize(images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] → f32 ((x/255) − mean)/std, the PT reference's
    ToTensor + Normalize (ref: ResNet/pytorch/train.py:320-324)."""
    x = images.astype(jnp.float32) / 255.0
    return (x - jnp.asarray(TORCH_CHANNEL_MEANS, jnp.float32)) / jnp.asarray(
        TORCH_CHANNEL_STDS, jnp.float32
    )


def tanh_normalize(images: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] → f32 [-1,1] (detection/pose/GAN nets — the
    reference's /127.5 - 1, e.g. YOLO/tensorflow/preprocess.py:24-25)."""
    return images.astype(jnp.float32) / 127.5 - 1.0


def maybe_normalize(images: jnp.ndarray, kind: str = "imagenet"):
    """Normalize on device iff the batch arrived as uint8."""
    if kind not in ("imagenet", "tanh", "torch"):
        raise ValueError(f"unknown normalization kind {kind!r}")
    if images.dtype != jnp.uint8:
        return images
    if kind == "imagenet":
        return imagenet_normalize(images)
    if kind == "torch":
        return torch_normalize(images)
    return tanh_normalize(images)
