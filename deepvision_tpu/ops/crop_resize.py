"""Device-resident crop/resize primitives for pipeline glue stages.

The serving DAG (``serve/pipeline.py``) composes models whose
geometries differ — detect at one input size, pose at another, with a
box-conditioned crop in between. The reference implementations do this
hop on the host (PIL crops between two model invocations); here every
primitive is pure fixed-shape ``jnp`` so the glue compiles into the
pipeline's device program and intermediate tensors never leave HBM:

- :func:`crop_and_resize` — batched box-conditioned bilinear crops
  (the ``tf.image.crop_and_resize`` analog): ``(B,H,W,C)`` images +
  ``(B,K,4)`` normalized corner boxes -> ``(B,K,S,S,C)`` crops, via
  per-box sampling grids and four-corner gathers. Degenerate boxes
  (the zero rows NMS padding produces) sample a clipped constant patch
  — garbage rows are masked by the caller's ``valid`` plane, exactly
  the engine's pad-isolation contract.
- :func:`resize_bilinear` — whole-image resize to a stage's input
  geometry (``jax.image.resize``, fixed output shape).

Everything here is shape-static: ``K`` and ``S`` are compile-time
constants, so ragged "people found per frame" traffic still hits one
executable per (stage, bucket) — raggedness lives in the mask, never
in the shapes (the compile-once discipline jaxlint JX105/JX110 pins).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["crop_and_resize", "resize_bilinear"]


def _crop_one(img: jnp.ndarray, box: jnp.ndarray, size: int) -> jnp.ndarray:
    """One ``(H,W,C)`` image x one normalized corner box -> ``(S,S,C)``
    bilinear crop. Sample points are the S pixel centers spanning the
    box; each samples the image with a 4-corner bilinear gather
    (edge-clamped, matching ``jax.image.resize``'s edge handling)."""
    h, w = img.shape[0], img.shape[1]
    # clamp to the image so junk NMS corners (saturated heads emit
    # +/-inf) can't poison the sample grid with NaN (0 * inf)
    box = jnp.clip(box, 0.0, 1.0)
    x1, y1, x2, y2 = box[0], box[1], box[2], box[3]
    # pixel-center sample coordinates in source-pixel space
    frac = (jnp.arange(size, dtype=jnp.float32) + 0.5) / size
    fy = (y1 + (y2 - y1) * frac) * h - 0.5
    fx = (x1 + (x2 - x1) * frac) * w - 0.5
    y0 = jnp.floor(fy)
    x0 = jnp.floor(fx)
    wy = (fy - y0)[:, None, None]
    wx = (fx - x0)[None, :, None]
    y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
    y1i = jnp.clip(y0i + 1, 0, h - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
    x1i = jnp.clip(x0i + 1, 0, w - 1)
    # gather rows then columns: (S,W,C) -> (S,S,C) per corner
    top = img[y0i][:, x0i] * (1 - wx) + img[y0i][:, x1i] * wx
    bot = img[y1i][:, x0i] * (1 - wx) + img[y1i][:, x1i] * wx
    return top * (1 - wy) + bot * wy


def crop_and_resize(images: jnp.ndarray, boxes: jnp.ndarray,
                    size: int) -> jnp.ndarray:
    """``(B,H,W,C)`` images + ``(B,K,4)`` normalized ``(x1,y1,x2,y2)``
    corner boxes -> ``(B,K,S,S,C)`` bilinear crops (float32)."""
    images = jnp.asarray(images, jnp.float32)
    boxes = jnp.asarray(boxes, jnp.float32)
    per_image = jax.vmap(_crop_one, in_axes=(None, 0, None))
    return jax.vmap(per_image, in_axes=(0, 0, None))(images, boxes, size)


def resize_bilinear(images: jnp.ndarray, size: int) -> jnp.ndarray:
    """``(B,H,W,C)`` -> ``(B,size,size,C)`` bilinear resize (float32)."""
    images = jnp.asarray(images, jnp.float32)
    b, _, _, c = images.shape
    return jax.image.resize(images, (b, size, size, c), method="bilinear")
