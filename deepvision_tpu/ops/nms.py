"""Fixed-shape batched non-maximum suppression for XLA.

The reference NMS is a per-image ``tf.map_fn`` with dynamic greedy loops
(ref: YOLO/tensorflow/postprocess.py:38-96) — uncompilable on TPU. Here the
same greedy-suppression semantics are expressed with static shapes:

1. top-K prefilter by score (score_thresh applied as -inf masking),
2. K×K IoU matrix once,
3. ``lax.fori_loop`` over K slots: the i-th best survivor kills all
   lower-scored boxes overlapping it above the threshold.

O(K²) on the VPU beats a data-dependent loop on TPU for K ≤ a few hundred
(max 100 detections, matching the reference). vmapped over the batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from deepvision_tpu.ops.iou import broadcast_iou

# Default greedy-suppression working-set bound; eval code compares the
# runtime candidate count against this same constant (the tripwire).
NMS_CANDIDATE_CAP = 512


def nms_indices(
    boxes, scores, *, iou_thresh: float = 0.5, score_thresh: float = 0.5,
    max_out: int = 100, candidate_cap: int = NMS_CANDIDATE_CAP,
):
    """boxes (N,4) corners, scores (N,) ->
    (idx (K,) int32 into the input, scores (K,), valid (K,) bool,
    n_candidates () int32), K=max_out. Survivors are compacted to the
    front in score order; padded slots have valid=False, score=0, idx=0.

    Greedy suppression runs over the top-``candidate_cap`` scored boxes
    (bounding the IoU matrix at cap², the fixed-shape price of XLA), then
    the first ``max_out`` survivors are emitted. Exact greedy-NMS parity
    holds whenever at most ``candidate_cap`` boxes clear ``score_thresh`` —
    size it accordingly (default 512 ≫ the reference's 100 detections,
    ref: postprocess.py:38-96). ``n_candidates`` is the runtime tripwire
    for that condition: the number of boxes that actually cleared
    ``score_thresh``. Whenever it exceeds ``candidate_cap`` (plausible
    early in training while objectness is uncalibrated), exactness has
    silently degraded — eval surfaces it as a metric.
    """
    n = boxes.shape[0]
    k = min(n, max(candidate_cap, max_out))
    n_candidates = jnp.sum(scores >= score_thresh).astype(jnp.int32)
    masked = jnp.where(scores >= score_thresh, scores, -jnp.inf)
    top_scores, top_idx = jax.lax.top_k(masked, k)
    iou = broadcast_iou(boxes[top_idx], boxes[top_idx])  # (k, k)

    def body(i, alive):
        kill = (iou[i] > iou_thresh) & (jnp.arange(k) > i) & alive[i]
        return alive & ~kill

    alive = jax.lax.fori_loop(0, k, body, top_scores > -jnp.inf)
    order = jnp.argsort(~alive, stable=True)  # survivors first, score order
    idx = top_idx[order][:max_out]
    out_scores = jnp.where(alive, top_scores, 0.0)[order][:max_out]
    valid = alive[order][:max_out]
    if k < max_out:
        pad = max_out - k
        idx = jnp.pad(idx, (0, pad))
        out_scores = jnp.pad(out_scores, (0, pad))
        valid = jnp.pad(valid, (0, pad))
    return idx, out_scores, valid, n_candidates


def batched_nms(boxes, scores, classes, *, iou_thresh=0.5, score_thresh=0.5,
                max_out=100, candidate_cap=NMS_CANDIDATE_CAP):
    """Class-agnostic greedy suppression over a batch (the reference's
    Postprocessor behavior — ref: postprocess.py:6-96).

    boxes (B,N,4), scores (B,N), classes (B,N) ->
    (boxes (B,K,4), scores (B,K), classes (B,K), valid (B,K),
    n_candidates (B,) — see :func:`nms_indices` on the exactness tripwire).
    """

    def one(b, s, c):
        idx, out_scores, valid, n_cand = nms_indices(
            b, s, iou_thresh=iou_thresh, score_thresh=score_thresh,
            max_out=max_out, candidate_cap=candidate_cap,
        )
        zero = jnp.zeros_like(valid)
        out_boxes = jnp.where(valid[:, None], b[idx], 0.0)
        out_classes = jnp.where(valid, c[idx], zero.astype(c.dtype))
        return out_boxes, out_scores, out_classes, valid, n_cand

    return jax.vmap(one)(boxes, scores, classes)
