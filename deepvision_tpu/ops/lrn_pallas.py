"""Pallas TPU kernel: fused Local Response Normalization.

LRN (AlexNet V1/V2, Inception V1 stem) is the zoo's one hot op with no
single XLA primitive: the jnp reference implementation
(ops/lrn.py) lowers to reduce_window + a chain of elementwise ops, each
a round-trip over the activation in HBM. This kernel fuses the whole
computation — square, 5-tap channel-window sum, ``(k + α/n·S)^β``
denominator, divide — into one VMEM-resident pass per row tile, so the
activation is read once and written once.

The channel window runs over the minor (lane) dimension inside the
block: a static Python loop of ``size`` shifted adds, which Mosaic turns
into lane rotations — no reduce_window, no padding round-trips.

Gradients: registered as ``jax.custom_vjp`` with an analytic backward in
plain jnp (the backward is bandwidth-bound over the same window; the
jnp form fuses well). Forward parity with ops/lrn.py is pinned to 1e-5
by tests (interpret mode on CPU, native on TPU).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deepvision_tpu.ops.lrn import local_response_norm

ROW_TILE = 256  # rows of the flattened (B·H·W, C) view per kernel instance


# Wide windows (Inception's stem LRN has size=192 over 192 channels)
# switch the window sum from unrolled lane rotations — whose ~size live
# temporaries blow the scoped-VMEM stack — to one banded matmul on the
# MXU: acc = sq @ W with W[j, i] = 1 iff j is inside channel i's window.
MATMUL_WINDOW_MIN = 16


def _lrn_kernel(x_ref, o_ref, *, size, alpha, beta, k):
    x = x_ref[...].astype(jnp.float32)
    sq = x * x
    half = size // 2
    c = x.shape[-1]
    if size >= MATMUL_WINDOW_MIN:
        # torch centering: window at channel i covers
        # j in [i - half, i + size - 1 - half], clipped to [0, c)
        j = jax.lax.broadcasted_iota(jnp.int32, (c, c), 0)
        i = jax.lax.broadcasted_iota(jnp.int32, (c, c), 1)
        band = ((j >= i - half) & (j <= i + size - 1 - half))
        acc = jax.lax.dot(sq, band.astype(jnp.float32),
                          precision=jax.lax.Precision.HIGHEST)
    else:
        acc = sq
        # shifted adds over the channel (lane) axis; window is centered
        # with torch semantics (half left, size-1-half right),
        # zero-padded edges
        for off in range(-half, size - half):
            if off == 0:
                continue
            shifted = jnp.roll(sq, -off, axis=-1)
            # zero the lanes that rolled around the edge
            idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
            valid = (idx + off >= 0) & (idx + off < c)
            acc = acc + jnp.where(valid, shifted, 0.0)
    denom = jnp.exp(beta * jnp.log(k + (alpha / size) * acc))
    o_ref[...] = (x / denom).astype(o_ref.dtype)


def _lrn_forward(x, size, alpha, beta, k, interpret):
    orig_shape = x.shape
    c = orig_shape[-1]
    rows = x.size // c
    x2 = x.reshape(rows, c)
    tile = min(ROW_TILE, rows)
    grid = (pl.cdiv(rows, tile),)
    out = pl.pallas_call(
        partial(_lrn_kernel, size=size, alpha=alpha, beta=beta, k=k),
        out_shape=jax.ShapeDtypeStruct((rows, c), x.dtype),
        grid=grid,
        in_specs=[pl.BlockSpec((tile, c), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((tile, c), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(x2)
    return out.reshape(orig_shape)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4, 5))
def local_response_norm_pallas(
    x: jax.Array,
    size: int = 5,
    alpha: float = 1e-4,
    beta: float = 0.75,
    k: float = 2.0,
    interpret: bool = False,
) -> jax.Array:
    """Drop-in for ops.lrn.local_response_norm (NHWC, trailing-channel
    window, torch semantics). ``interpret=True`` runs the kernel in the
    Pallas interpreter (CPU tests)."""
    return _lrn_forward(x, size, alpha, beta, k, interpret)


def _fwd(x, size, alpha, beta, k, interpret):
    return _lrn_forward(x, size, alpha, beta, k, interpret), x


def _window_sum(v, size, *, mirrored: bool = False):
    """Channel-window sum with torch centering; ``mirrored`` swaps the
    padding offsets (the adjoint window used by the backward pass)."""
    half = size // 2
    lo, hi = (size - 1 - half, half) if mirrored else (half, size - 1 - half)
    pad = [(0, 0)] * (v.ndim - 1) + [(lo, hi)]
    return jax.lax.reduce_window(
        v, 0.0, jax.lax.add,
        window_dimensions=[1] * (v.ndim - 1) + [size],
        window_strides=[1] * v.ndim,
        padding=pad,
    )


def _bwd(size, alpha, beta, k, interpret, x, g):
    """Analytic VJP: y = x·d^−β with d = k + (α/n)·S(x²);
    dx = g·d^−β − (2αβ/n)·x·S̃(g·x·d^−β−1), S̃ = the adjoint (same,
    symmetric-ish) channel-window sum with mirrored padding offsets."""
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    d = k + (alpha / size) * _window_sum(x32 * x32, size)
    d_mb = jnp.exp(-beta * jnp.log(d))
    inner = g32 * x32 * d_mb / d
    # adjoint of the forward window = the same window with mirrored padding
    adj = _window_sum(inner, size, mirrored=True)
    dx = g32 * d_mb - (2.0 * alpha * beta / size) * x32 * adj
    return (dx.astype(x.dtype),)


local_response_norm_pallas.defvjp(_fwd, _bwd)


__all__ = ["local_response_norm_pallas", "local_response_norm"]
