"""Box coordinate conversions + broadcast IoU (pure jnp, jit-able).

Semantics parity with ref: YOLO/tensorflow/utils.py:4-85 (xywh↔corner
conversions, gluon-cv-derived broadcast IoU, clipped manual BCE).
"""

from __future__ import annotations

import jax.numpy as jnp


def xywh_to_corners(xywh):
    """[..., (cx, cy, w, h)] -> [..., (x1, y1, x2, y2)]."""
    xy, wh = xywh[..., :2], xywh[..., 2:4]
    return jnp.concatenate([xy - wh / 2, xy + wh / 2], axis=-1)


def corners_to_xywh(corners):
    p1, p2 = corners[..., :2], corners[..., 2:4]
    return jnp.concatenate([(p1 + p2) / 2, p2 - p1], axis=-1)


def broadcast_iou(box_a, box_b):
    """IoU of every a-box against every b-box.

    box_a: (..., A, 4) corners; box_b: (..., B, 4) corners -> (..., A, B).
    """
    a = box_a[..., :, None, :]
    b = box_b[..., None, :, :]
    inter_lo = jnp.maximum(a[..., :2], b[..., :2])
    inter_hi = jnp.minimum(a[..., 2:4], b[..., 2:4])
    inter_wh = jnp.maximum(inter_hi - inter_lo, 0.0)
    inter = inter_wh[..., 0] * inter_wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(
        a[..., 3] - a[..., 1], 0.0
    )
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0.0
    )
    return inter / jnp.maximum(area_a + area_b - inter, 1e-9)


def binary_cross_entropy(pred_prob, labels, *, eps: float = 1e-7):
    """Clipped elementwise BCE on probabilities
    (ref: YOLO/tensorflow/utils.py binary_cross_entropy)."""
    p = jnp.clip(pred_prob, eps, 1.0 - eps)
    return -(labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p))
