"""Pose PCK / PCKh — the accuracy metric the reference never reported
(SURVEY §6: "Hourglass PCKh ... not reported").

PCK@τ: a predicted keypoint is correct when its distance to the ground
truth is < τ × a per-sample normalization length — the MPII convention
uses the head-segment size (PCKh, τ = 0.5); with only the person scale
available, ``scale × 200`` (the MPII body height) times a head fraction
is the standard fallback.
"""

from __future__ import annotations

import numpy as np


def pck(
    pred_xy: np.ndarray,
    true_xy: np.ndarray,
    visible: np.ndarray,
    norm_length: np.ndarray,
    *,
    threshold: float = 0.5,
) -> dict:
    """(B, K, 2) predicted + true coords (any consistent units),
    (B, K) visibility, (B,) per-sample normalization length →
    {'pck': scalar, 'per_joint': (K,), 'count': (K,)} over visible
    joints only.
    """
    pred_xy = np.asarray(pred_xy, np.float64)
    true_xy = np.asarray(true_xy, np.float64)
    vis = np.asarray(visible) > 0
    norm = np.asarray(norm_length, np.float64)[:, None]
    dist = np.linalg.norm(pred_xy - true_xy, axis=-1)  # (B, K)
    correct = (dist < threshold * np.maximum(norm, 1e-12)) & vis
    count = vis.sum(axis=0)
    per_joint = np.where(
        count > 0, correct.sum(axis=0) / np.maximum(count, 1), np.nan
    )
    total_vis = vis.sum()
    return {
        "pck": float(correct.sum() / total_vis) if total_vis else 0.0,
        "per_joint": per_joint,
        "count": count,
    }


def heatmap_argmax_keypoints(heatmaps: np.ndarray) -> np.ndarray:
    """(B, H, W, K) heatmaps → (B, K, 2) (x, y) peak coordinates in
    heatmap cells (the decoding the demo/eval path uses)."""
    b, h, w, k = heatmaps.shape
    flat = heatmaps.reshape(b, h * w, k).argmax(axis=1)  # (B, K)
    ys, xs = np.divmod(flat, w)
    return np.stack([xs, ys], axis=-1).astype(np.float64)
