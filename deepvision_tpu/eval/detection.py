"""Detection mAP — completing the reference's explicit WIP
(ref: YOLO/tensorflow/README.md:28 "mAP ... working in progress").

Standard PASCAL VOC evaluation: per class, detections across the whole
set are sorted by score and greedily matched to ground truth at
IoU ≥ ``iou_thresh`` (each GT matches at most once; duplicates are false
positives), giving a precision/recall curve summarized as AP by either
the VOC2007 11-point rule or the continuous area-under-curve
(VOC2010+/COCO-style at a single IoU). Host-side numpy: evaluation is
offline bookkeeping, not a compiled hot path.
"""

from __future__ import annotations

import numpy as np


def _box_iou(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(N, 4) × (M, 4) corner boxes → (N, M) IoU. Non-finite boxes
    (untrained nets can emit exp-overflow sizes) count as zero overlap."""
    a = np.where(np.isfinite(a), a, 0.0)
    b = np.where(np.isfinite(b), b, 0.0)
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    inter = np.clip(rb - lt, 0, None).prod(-1)
    area_a = np.clip(a[:, 2:] - a[:, :2], 0, None).prod(-1)
    area_b = np.clip(b[:, 2:] - b[:, :2], 0, None).prod(-1)
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def average_precision(
    recall: np.ndarray, precision: np.ndarray, *, method: str = "area"
) -> float:
    """Summarize a PR curve: ``area`` (VOC2010+) or ``11point`` (VOC2007)."""
    if method == "11point":
        ap = 0.0
        for t in np.linspace(0, 1, 11):
            mask = recall >= t
            ap += (precision[mask].max() if mask.any() else 0.0) / 11.0
        return float(ap)
    if method != "area":
        raise ValueError(f"unknown AP method {method!r}")
    # precision envelope + area under the stepwise curve
    r = np.concatenate([[0.0], recall, [1.0]])
    p = np.concatenate([[0.0], precision, [0.0]])
    p = np.maximum.accumulate(p[::-1])[::-1]
    idx = np.where(r[1:] != r[:-1])[0]
    return float(np.sum((r[idx + 1] - r[idx]) * p[idx + 1]))


def evaluate_map(
    detections: list[dict],
    ground_truths: list[dict],
    num_classes: int,
    *,
    iou_thresh: float = 0.5,
    method: str = "area",
) -> dict:
    """Corpus mAP.

    Per image i: ``detections[i]`` = {'boxes' (N,4) corners, 'scores'
    (N,), 'classes' (N,)}; ``ground_truths[i]`` = {'boxes' (M,4),
    'classes' (M,)}. Returns {'map', 'ap': (C,), 'num_gt': (C,)}
    (classes with no ground truth get AP = nan and are excluded from the
    mean).
    """
    if len(detections) != len(ground_truths):
        raise ValueError("detections and ground_truths length mismatch")
    aps = np.full(num_classes, np.nan)
    num_gt = np.zeros(num_classes, np.int64)
    for c in range(num_classes):
        records = []  # (score, is_tp)
        total_gt = 0
        for det, gt in zip(detections, ground_truths):
            gt_mask = np.asarray(gt["classes"]) == c
            gt_boxes = np.asarray(gt["boxes"], np.float64)[gt_mask]
            total_gt += len(gt_boxes)
            det_mask = np.asarray(det["classes"]) == c
            boxes = np.asarray(det["boxes"], np.float64)[det_mask]
            scores = np.asarray(det["scores"], np.float64)[det_mask]
            order = np.argsort(-scores)
            matched = np.zeros(len(gt_boxes), bool)
            ious = _box_iou(boxes, gt_boxes) if len(gt_boxes) else None
            for d in order:
                if ious is None:
                    records.append((scores[d], False))
                    continue
                j = int(np.argmax(ious[d]))
                if ious[d, j] >= iou_thresh and not matched[j]:
                    matched[j] = True
                    records.append((scores[d], True))
                else:
                    records.append((scores[d], False))
        num_gt[c] = total_gt
        if total_gt == 0:
            continue  # AP undefined for absent classes
        if not records:
            aps[c] = 0.0
            continue
        records.sort(key=lambda r: -r[0])
        tp = np.cumsum([r[1] for r in records])
        fp = np.cumsum([not r[1] for r in records])
        recall = tp / total_gt
        precision = tp / np.maximum(tp + fp, 1)
        aps[c] = average_precision(recall, precision, method=method)
    return {
        "map": float(np.nanmean(aps)) if np.isfinite(aps).any() else 0.0,
        "ap": aps,
        "num_gt": num_gt,
    }
