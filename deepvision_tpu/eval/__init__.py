"""Evaluation metrics the reference never finished.

- detection mAP (the reference's README lists it as "working in
  progress", ref: YOLO/tensorflow/README.md:28) — eval/detection.py
- pose PCK/PCKh (never reported by the reference) — eval/pose.py
"""

from deepvision_tpu.eval.detection import average_precision, evaluate_map
from deepvision_tpu.eval.pose import pck

__all__ = ["average_precision", "evaluate_map", "pck"]
