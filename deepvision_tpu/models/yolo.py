"""Darknet-53 backbone + YOLO v3 three-scale detector (Flax, NHWC).

Capability parity with ref: YOLO/tensorflow/yolov3.py:23-235 — Darknet-53
(conv-BN-leaky(0.1) everywhere, residual stacks 1/2/8/8/4 emitting three
feature scales) and the FPN-style detector head (5-conv blocks, nearest
upsample + concat, final linear 1x1 conv to 3*(5+C) channels) — redesigned
as Flax modules rather than a Keras graph: raw grid outputs are returned
always; box decoding is a separate pure function (ops/yolo_decode) applied
by the caller (loss or postprocess), keeping the model jit-friendly and
the train/infer asymmetry (ref models return different outputs per mode,
yolov3.py:221-235) out of the module.

Outputs are ordered (small, medium, large) grids = strides (8, 16, 32),
matching the reference's (y_small=52², y_medium=26², y_large=13²) at 416².
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepvision_tpu.models.layers import ConvBN, global_avg_pool
from deepvision_tpu.models.registry import register
from deepvision_tpu.parallel.constraint import guard_thin_h

Dtype = Any


def leaky(x):
    return nn.leaky_relu(x, negative_slope=0.1)


class DarknetBlock(nn.Module):
    """1x1 squeeze → 3x3 expand residual (ref: yolov3.py:44-51)."""

    features: int  # output channels (= input channels)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        shortcut = x
        x = ConvBN(self.features // 2, (1, 1), act=leaky,
                   dtype=self.dtype, name="squeeze")(x, train)
        x = ConvBN(self.features, (3, 3), act=leaky,
                   dtype=self.dtype, name="expand")(x, train)
        return shortcut + x


class Darknet53(nn.Module):
    """Backbone emitting (stride-8, stride-16, stride-32) feature maps.

    Stage depths (1, 2, 8, 8, 4) — ref: yolov3.py:54-92 / YOLOv3 Table 1.
    """

    stage_blocks: Sequence[int] = (1, 2, 8, 8, 4)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = ConvBN(32, (3, 3), act=leaky, dtype=self.dtype, name="stem")(
            x, train
        )
        outputs = []
        features = 32
        for stage, blocks in enumerate(self.stage_blocks):
            features *= 2
            x = ConvBN(
                features, (3, 3), strides=(2, 2), act=leaky,
                dtype=self.dtype, name=f"down{stage}",
            )(x, train)
            # under spatial partitioning, drop the H sharding once this
            # stage's map is too thin — XLA SPMD miscomputes the
            # strided-conv + residual backward at 1-row H shards
            # (parallel/constraint.py; no-op outside a spatial mesh)
            x = guard_thin_h(x)
            for b in range(blocks):
                x = DarknetBlock(
                    features, dtype=self.dtype, name=f"stage{stage}_block{b}"
                )(x, train)
            if stage >= 2:  # strides 8, 16, 32
                outputs.append(x)
        return tuple(outputs)


class DarknetClassifier(nn.Module):
    """Darknet-53 as an ImageNet classifier (GAP → Dense), the standard
    pretraining configuration for the detector backbone."""

    num_classes: int = 1000
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        feats = Darknet53(dtype=self.dtype, name="backbone")(x, train)
        x = global_avg_pool(feats[-1])
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(
            x.astype(jnp.float32)
        )


class _HeadBlock(nn.Module):
    """The 5-conv alternating 1x1/3x3 block + detection output conv
    (ref: yolov3.py:109-205). Returns (branch, raw_grid)."""

    features: int  # the 1x1 width; 3x3 convs use 2x
    out_channels: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f, d = self.features, self.dtype
        for i in range(3):
            x = ConvBN(f, (1, 1), act=leaky, dtype=d, name=f"conv1x1_{i}")(
                x, train
            )
            if i < 2:
                x = ConvBN(2 * f, (3, 3), act=leaky, dtype=d,
                           name=f"conv3x3_{i}")(x, train)
        branch = x  # feeds the next (finer) scale
        x = ConvBN(2 * f, (3, 3), act=leaky, dtype=d, name="conv3x3_2")(
            x, train
        )
        # final conv is linear with bias, f32 out (ref: yolov3.py:127-133)
        x = nn.Conv(self.out_channels, (1, 1), use_bias=True,
                    dtype=jnp.float32, name="out")(x.astype(jnp.float32))
        return branch, x


def _upsample2x(x):
    """Nearest-neighbor 2x (the reference's UpSampling2D/darknet upsample)."""
    b, h, w, c = x.shape
    x = jnp.broadcast_to(x[:, :, None, :, None, :], (b, h, 2, w, 2, c))
    return x.reshape(b, 2 * h, 2 * w, c)


class YoloV3(nn.Module):
    """Three-scale detector; returns raw grids (B, S, S, 3, 5+C) ordered
    (small-objects 52², medium 26², large 13²) at 416² input."""

    num_classes: int = 20
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        out_ch = 3 * (5 + self.num_classes)
        d = self.dtype
        feat_s, feat_m, feat_l = Darknet53(dtype=d, name="backbone")(x, train)

        branch, y_large = _HeadBlock(512, out_ch, dtype=d, name="head_large")(
            feat_l, train
        )
        x = ConvBN(256, (1, 1), act=leaky, dtype=d, name="lateral_medium")(
            branch, train
        )
        # thin-H spatial guards on the merge points: the FPN's
        # upsample+concat graph miscomputes backward under thin H
        # shards even at widths where plain chains are exact
        # (parallel/constraint.py; no-ops outside a spatial mesh)
        x = guard_thin_h(jnp.concatenate([_upsample2x(x), feat_m],
                                         axis=-1))
        branch, y_medium = _HeadBlock(256, out_ch, dtype=d,
                                      name="head_medium")(x, train)
        x = ConvBN(128, (1, 1), act=leaky, dtype=d, name="lateral_small")(
            branch, train
        )
        x = guard_thin_h(jnp.concatenate([_upsample2x(x), feat_s],
                                         axis=-1))
        _, y_small = _HeadBlock(128, out_ch, dtype=d, name="head_small")(
            x, train
        )

        def split_anchors(y):
            b, h, w, _ = y.shape
            return y.reshape(b, h, w, 3, 5 + self.num_classes)

        return (
            split_anchors(y_small),
            split_anchors(y_medium),
            split_anchors(y_large),
        )


@register("darknet53")
def make_darknet53(num_classes: int = 1000, dtype=jnp.float32, **_):
    return DarknetClassifier(num_classes=num_classes, dtype=dtype)


@register("yolov3")
def make_yolov3(num_classes: int = 20, dtype=jnp.float32, **_):
    return YoloV3(num_classes=num_classes, dtype=dtype)
