"""VGG-16 (config D) and VGG-19 (config E).

ref: VGG/pytorch/models/vgg16.py:8-127 / vgg19.py. Xavier conv init +
N(0, 0.01) linear init — the reference documents this choice as necessary
for convergence (ref: vgg16.py:113-119) — reproduced here.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.layers import xavier_uniform
from deepvision_tpu.models.registry import register

_CFG = {
    "vgg16": (2, 2, 3, 3, 3),
    "vgg19": (2, 2, 4, 4, 4),
}
_FILTERS = (64, 128, 256, 512, 512)

normal_001 = nn.initializers.normal(stddev=0.01)


class VGG(nn.Module):
    stage_convs: Sequence[int]
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for i, (n, f) in enumerate(zip(self.stage_convs, _FILTERS)):
            for j in range(n):
                x = nn.relu(
                    nn.Conv(f, (3, 3), padding="SAME",
                            kernel_init=xavier_uniform, dtype=self.dtype,
                            name=f"conv{i + 1}_{j + 1}")(x)
                )
            x = layers.max_pool(x)
        x = x.reshape((x.shape[0], -1))  # 7*7*512
        x = nn.relu(nn.Dense(4096, kernel_init=normal_001,
                             dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, kernel_init=normal_001,
                             dtype=self.dtype, name="fc2")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, kernel_init=normal_001,
                        dtype=jnp.float32, name="fc3")(x)


@register("vgg16")
def _vgg16(**kw):
    return VGG(stage_convs=_CFG["vgg16"], **kw)


@register("vgg19")
def _vgg19(**kw):
    return VGG(stage_convs=_CFG["vgg19"], **kw)
