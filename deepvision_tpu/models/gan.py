"""Generative models: DCGAN (MNIST) and CycleGAN G/D (Flax, NHWC).

Capability parity with the reference:

- DCGAN generator/discriminator — ref: DCGAN/tensorflow/models.py:8-65
  (Dense→reshape→3 transposed convs w/ BN+LeakyReLU, tanh head; 2-conv
  LeakyReLU+Dropout discriminator with a single logit).
- CycleGAN 9-ResNet-block generator with reflection padding and a 70×70
  PatchGAN discriminator — ref: CycleGAN/tensorflow/models.py:8-104.
  The reference uses BatchNorm where the CycleGAN paper uses
  InstanceNorm; we keep BatchNorm for behavior parity and expose
  ``norm='instance'`` as the paper-accurate option.

All are plain Flax modules; the two-network training dynamics live in
train/gan.py (the reference embeds them in per-model scripts).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models.registry import register

Dtype = Any


def leaky(x, slope=0.3):
    return nn.leaky_relu(x, negative_slope=slope)


class DCGANGenerator(nn.Module):
    """z (B, noise_dim) → (B, 28, 28, 1) in [-1, 1] (tanh)."""

    noise_dim: int = 100
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = False):
        d = self.dtype

        def bn(x, name):
            return nn.BatchNorm(use_running_average=not train,
                                dtype=jnp.float32, name=name)(x)

        x = nn.Dense(7 * 7 * 256, use_bias=False, dtype=d, name="fc")(z)
        x = leaky(bn(x, "bn0"))
        x = x.reshape(x.shape[0], 7, 7, 256)
        x = nn.ConvTranspose(128, (5, 5), strides=(1, 1), padding="SAME",
                             use_bias=False, dtype=d, name="deconv1")(x)
        x = leaky(bn(x, "bn1"))
        x = nn.ConvTranspose(64, (5, 5), strides=(2, 2), padding="SAME",
                             use_bias=False, dtype=d, name="deconv2")(x)
        x = leaky(bn(x, "bn2"))
        x = nn.ConvTranspose(1, (5, 5), strides=(2, 2), padding="SAME",
                             use_bias=False, dtype=jnp.float32,
                             name="deconv3")(x.astype(jnp.float32))
        return jnp.tanh(x)


class DCGANDiscriminator(nn.Module):
    """(B, 28, 28, 1) → (B, 1) real/fake logit."""

    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        x = nn.Conv(64, (5, 5), strides=(2, 2), padding="SAME", dtype=d,
                    name="conv1")(x)
        x = leaky(x)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = nn.Conv(128, (5, 5), strides=(2, 2), padding="SAME", dtype=d,
                    name="conv2")(x)
        x = leaky(x)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = x.reshape(x.shape[0], -1)
        return nn.Dense(1, dtype=jnp.float32,
                        name="fc")(x.astype(jnp.float32))


def reflect_pad(x, pad: int):
    """NHWC reflection padding (ref ReflectionPad2d, models.py:8-14)."""
    return jnp.pad(
        x, ((0, 0), (pad, pad), (pad, pad), (0, 0)), mode="reflect"
    )


class _Norm(nn.Module):
    """BatchNorm (ref parity) or InstanceNorm (paper)."""

    kind: str = "batch"

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.kind == "instance":
            return nn.InstanceNorm(dtype=jnp.float32, name="norm")(x)
        return nn.BatchNorm(use_running_average=not train,
                            dtype=jnp.float32, name="norm")(x)


class CycleGANResBlock(nn.Module):
    """reflect-pad valid 3x3 conv ×2 with norm, residual add
    (ref: models.py:17-38)."""

    features: int = 256
    norm: str = "batch"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        y = reflect_pad(x, 1)
        y = nn.Conv(self.features, (3, 3), padding="VALID", use_bias=False,
                    dtype=d, name="conv1")(y)
        y = nn.relu(_Norm(self.norm, name="norm1")(y, train))
        y = reflect_pad(y, 1)
        y = nn.Conv(self.features, (3, 3), padding="VALID", use_bias=False,
                    dtype=d, name="conv2")(y)
        y = _Norm(self.norm, name="norm2")(y, train)
        return x + y


class CycleGANGenerator(nn.Module):
    """c7s1-64, d128, d256, R256×n, u128, u64, c7s1-3 + tanh
    (ref: models.py:41-79)."""

    n_blocks: int = 9
    norm: str = "batch"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype

        def norm(x, name):
            return _Norm(self.norm, name=name)(x, train)

        x = reflect_pad(x, 3)
        x = nn.Conv(64, (7, 7), padding="VALID", use_bias=False, dtype=d,
                    name="stem")(x)
        x = nn.relu(norm(x, "stem_norm"))
        x = nn.Conv(128, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d, name="down1")(x)
        x = nn.relu(norm(x, "down1_norm"))
        x = nn.Conv(256, (3, 3), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d, name="down2")(x)
        x = nn.relu(norm(x, "down2_norm"))
        for i in range(self.n_blocks):
            x = CycleGANResBlock(256, self.norm, dtype=d,
                                 name=f"res{i}")(x, train)
        x = nn.ConvTranspose(128, (3, 3), strides=(2, 2), padding="SAME",
                             use_bias=False, dtype=d, name="up1")(x)
        x = nn.relu(norm(x, "up1_norm"))
        x = nn.ConvTranspose(64, (3, 3), strides=(2, 2), padding="SAME",
                             use_bias=False, dtype=d, name="up2")(x)
        x = nn.relu(norm(x, "up2_norm"))
        x = reflect_pad(x, 3)
        x = nn.Conv(3, (7, 7), padding="VALID", dtype=jnp.float32,
                    name="head")(x.astype(jnp.float32))
        return jnp.tanh(x)


class CycleGANDiscriminator(nn.Module):
    """70×70 PatchGAN: C64-C128-C256-C512 + 1-ch patch logits
    (ref: models.py:82-104)."""

    norm: str = "batch"
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype

        def norm(x, name):
            return _Norm(self.norm, name=name)(x, train)

        x = nn.Conv(64, (4, 4), strides=(2, 2), padding="SAME", dtype=d,
                    name="conv1")(x)
        x = leaky(x, 0.2)
        x = nn.Conv(128, (4, 4), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d, name="conv2")(x)
        x = leaky(norm(x, "norm2"), 0.2)
        x = nn.Conv(256, (4, 4), strides=(2, 2), padding="SAME",
                    use_bias=False, dtype=d, name="conv3")(x)
        x = leaky(norm(x, "norm3"), 0.2)
        x = nn.Conv(512, (4, 4), strides=(1, 1), padding="SAME",
                    use_bias=False, dtype=d, name="conv4")(x)
        x = leaky(norm(x, "norm4"), 0.2)
        return nn.Conv(1, (4, 4), strides=(1, 1), padding="SAME",
                       dtype=jnp.float32, name="head")(
            x.astype(jnp.float32)
        )


@register("dcgan_generator")
def dcgan_generator(dtype: Dtype = jnp.float32, **kw) -> DCGANGenerator:
    return DCGANGenerator(dtype=dtype, **kw)


@register("dcgan_discriminator")
def dcgan_discriminator(dtype: Dtype = jnp.float32,
                        **kw) -> DCGANDiscriminator:
    return DCGANDiscriminator(dtype=dtype, **kw)


@register("cyclegan_generator")
def cyclegan_generator(dtype: Dtype = jnp.float32,
                       **kw) -> CycleGANGenerator:
    return CycleGANGenerator(dtype=dtype, **kw)


@register("cyclegan_discriminator")
def cyclegan_discriminator(dtype: Dtype = jnp.float32,
                           **kw) -> CycleGANDiscriminator:
    return CycleGANDiscriminator(dtype=dtype, **kw)
