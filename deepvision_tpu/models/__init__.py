from deepvision_tpu.models.registry import get_model, list_models, register

# Import for registration side effects.
from deepvision_tpu.models import lenet  # noqa: F401

__all__ = ["get_model", "list_models", "register"]
