from deepvision_tpu.models.registry import get_model, list_models, register

# Imports for registration side effects.
from deepvision_tpu.models import (  # noqa: F401
    alexnet,
    centernet,
    gan,
    hourglass,
    inception,
    lenet,
    mobilenet,
    resnet,
    shufflenet,
    vgg,
    yolo,
)

__all__ = ["get_model", "list_models", "register"]
