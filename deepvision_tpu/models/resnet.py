"""ResNet family — the framework's flagship (north-star config).

V1 variants re-express the PyTorch reference:
- ResNet-34: BasicBlock stacks (3,4,6,3). NOTE a reference defect found in
  round 2: the ref's shipped resnet34.py builds (2,2,2,2) stacks — an
  18-layer topology (11.69M params) contradicting its own "34-layer
  column" comment (ref: resnet34.py:38-41) and its committed log's
  23.38M-param summary. We implement the paper's 34-layer depth (and keep
  the ref's projection quirk below); param counts pinned in
  tests/test_models_classification.py.
- ResNet-50: BottleneckBlock 1x1-3x3-1x1 stacks (3,4,6,3) —
  ref: ResNet/pytorch/models/resnet50.py:8-165.
- ResNet-152: same with (3,8,36,3) — ref: ResNet/pytorch/models/resnet152.py:38-39.

Init parity: he-normal convs, BN gamma=1 beta=0 (ref: resnet50.py:84-93).

Activation parity (for the checkpoint converter's layer-for-layer diff):
- the reference puts the downsampling stride on the FIRST 1x1 of the
  bottleneck (original-ResNet layout, ref: resnet50.py:100-108), NOT on the
  3x3 as torchvision v1.5 does — matched here;
- strided convs/pools use explicit symmetric (torch-style) padding, since
  XLA "SAME" pads asymmetrically under stride 2 (e.g. (2,3) vs torch's
  (3,3) on the 7x7 stem) and would shift border activations.

Reference quirk kept behind ``always_project`` (default True for checkpoint-
converter parity): the first block of EVERY group gets a projection shortcut
even when stride=1 and channels match (ResNet-34 group 1), adding params vs
the paper — ref: resnet34.py:69-75. Set False for the paper-faithful net.

ResNet-50 V2 is the pre-activation variant (BN-ReLU before conv, stem without
BN, final BN-ReLU before GAP) — ref: ResNet/tensorflow/models/resnet50v2.py:18-171.
The TF reference's in-model softmax (resnet50.py:42) is normalized away: all
variants emit logits.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax import lax

from deepvision_tpu.models import layers
from deepvision_tpu.models.layers import ConvBN, he_normal
from deepvision_tpu.models.registry import register


class _Conv7S2D(nn.Module):
    """The 7x7/2 stem conv re-expressed over a 2x2 space-to-depth input
    (the standard MLPerf TPU ResNet reformulation): 3-channel 224² maps
    tile terribly onto the MXU's 8x128 lanes, so fold the stride-2
    spatial structure into channels (12) and run a 4x4/1 VALID conv.

    The PARAMETER stays the canonical ``[7,7,Cin,out]`` kernel (same
    name/shape as ``nn.Conv`` — checkpoints and the torch converter are
    unaffected); the layout transform is two reshapes per step on a
    ~37 KB tensor. Numerically identical to the torch-padded 7x7/2 conv
    (pinned by tests/test_models_classification.py).

    Derivation (per spatial axis): torch pad 3 means output m reads
    x[2m-3 .. 2m+3]. Pad x by (4, 2) so P[r'] = x[r'-4]; then the taps
    are P[2m+1 .. 2m+7] ⊂ P[2(m+ki)+di] for ki∈[0,4), di∈{0,1} with
    kernel row kr = 2ki+di-1 — i.e. the 7-tap kernel left-padded by one
    zero row/col to 8 and reshaped (4,2,4,2,...)."""

    features: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        n, h, w, c = x.shape
        if h % 2 or w % 2:
            raise ValueError(f"s2d stem needs even H/W, got {(h, w)}")
        kernel = self.param("kernel", he_normal,
                            (7, 7, c, self.features), jnp.float32)
        x = x.astype(self.dtype)
        k = kernel.astype(self.dtype)

        p = jnp.pad(x, ((0, 0), (4, 2), (4, 2), (0, 0)))
        hp, wp = h + 6, w + 6
        s = p.reshape(n, hp // 2, 2, wp // 2, 2, c)
        s = s.transpose(0, 1, 3, 2, 4, 5).reshape(n, hp // 2, wp // 2,
                                                  4 * c)

        k8 = jnp.pad(k, ((1, 0), (1, 0), (0, 0), (0, 0)))
        k8 = k8.reshape(4, 2, 4, 2, c, self.features)
        k8 = k8.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * c,
                                                    self.features)
        return lax.conv_general_dilated(
            s, k8, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class S2DStem(nn.Module):
    """ConvBN-shaped stem (children ``conv``/``bn``, identical pytree)
    computing the 7x7/2 conv via :class:`_Conv7S2D`."""

    features: int = 64
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = _Conv7S2D(self.features, dtype=self.dtype, name="conv")(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype, name="bn")(x)
        return nn.relu(x)


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    features: int
    strides: int = 1
    project: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        y = ConvBN(self.features, (3, 3), (self.strides,) * 2,
                   padding=((1, 1), (1, 1)),
                   dtype=self.dtype, name="conv1")(x, train)
        y = ConvBN(self.features, (3, 3), act=None,
                   dtype=self.dtype, name="conv2")(y, train)
        if self.project:
            residual = ConvBN(self.features, (1, 1), (self.strides,) * 2,
                              act=None, dtype=self.dtype, name="proj")(x, train)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 reduce → 3x3 → 1x1 expand (×4), stride on the 3x3 (torchvision/
    reference convention — ref: ResNet/pytorch/models/resnet50.py:24-47)."""

    features: int  # bottleneck width; output is features * 4
    strides: int = 1
    project: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        residual = x
        # stride on the 1x1 reduce — the reference's (original-paper)
        # layout, ref: resnet50.py:100-108; torchvision v1.5 differs
        y = ConvBN(self.features, (1, 1), (self.strides,) * 2,
                   dtype=self.dtype, name="conv1")(x, train)
        y = ConvBN(self.features, (3, 3), padding=((1, 1), (1, 1)),
                   dtype=self.dtype, name="conv2")(y, train)
        y = ConvBN(self.features * 4, (1, 1), act=None,
                   dtype=self.dtype, name="conv3")(y, train)
        if self.project:
            residual = ConvBN(self.features * 4, (1, 1), (self.strides,) * 2,
                              act=None, dtype=self.dtype, name="proj")(x, train)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block: type = BottleneckBlock
    num_classes: int = 1000
    num_filters: int = 64
    always_project: bool = True
    s2d_stem: bool = False
    # activation rematerialization per residual block (HBM-traffic /
    # memory lever; parameter pytree is unchanged — nn.remat is a
    # lifted transform preserving module names):
    #   None    — save what XLA saves (default)
    #   "block" — save only block boundaries; recompute everything
    #             inside each block during backward
    #   "conv"  — save only conv outputs (ConvBN's "conv_out"
    #             checkpoint_name); recompute the BN/ReLU elementwise
    #             chain fused into backward consumers
    remat: str | None = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        if self.s2d_stem:
            x = S2DStem(self.num_filters, dtype=self.dtype,
                        name="stem")(x, train)
        else:
            x = ConvBN(self.num_filters, (7, 7), (2, 2),
                       padding=((3, 3), (3, 3)),
                       dtype=self.dtype, name="stem")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        block_cls = self.block
        if self.remat is not None:
            import jax

            policy = (
                jax.checkpoint_policies.save_only_these_names("conv_out")
                if self.remat == "conv" else None  # "block": save nothing
            )
            # prevent_cse=True (the jax.checkpoint default): blocks are
            # unrolled, not scanned, so without the optimization
            # barriers XLA's CSE simply undoes the recompute — measured
            # on v5e: prevent_cse=False compiled to the identical
            # program (same flops/bytes) as no remat at all
            block_cls = nn.remat(
                self.block, prevent_cse=True, static_argnums=(2,),
                policy=policy,
            )
        for i, n_blocks in enumerate(self.stage_sizes):
            feats = self.num_filters * (2 ** i)
            for j in range(n_blocks):
                strides = 2 if (i > 0 and j == 0) else 1
                first = j == 0
                project = first and (
                    self.always_project
                    or strides != 1
                    or self.block is BottleneckBlock
                )
                x = block_cls(
                    feats, strides=strides, project=project,
                    dtype=self.dtype, name=f"stage{i + 1}_block{j + 1}",
                )(x, train)
        x = layers.global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        kernel_init=he_normal, name="fc")(x)


class PreActBottleneck(nn.Module):
    """V2 pre-activation bottleneck (ref: resnet50v2.py block fns)."""

    features: int
    strides: int = 1
    project: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        pre = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                           epsilon=1.001e-5, dtype=jnp.float32,
                           name="preact_bn")(x)
        pre = nn.relu(pre)
        if self.project:
            residual = nn.Conv(self.features * 4, (1, 1),
                               strides=(self.strides,) * 2, use_bias=True,
                               kernel_init=he_normal, dtype=self.dtype,
                               name="proj")(pre)
        elif self.strides > 1:
            residual = layers.max_pool(x, (1, 1), (self.strides,) * 2)
        else:
            residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False,
                    kernel_init=he_normal, dtype=self.dtype, name="conv1")(pre)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1.001e-5, dtype=jnp.float32, name="bn1")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), strides=(self.strides,) * 2,
                    padding=((1, 1), (1, 1)), use_bias=False,
                    kernel_init=he_normal,
                    dtype=self.dtype, name="conv2")(y)
        y = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1.001e-5, dtype=jnp.float32, name="bn2")(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=True,
                    kernel_init=he_normal, dtype=self.dtype, name="conv3")(y)
        return y + residual


class ResNetV2(nn.Module):
    """Pre-activation ResNet (keras-applications structure —
    ref: ResNet/tensorflow/models/resnet50v2.py:18-171). Strides live on the
    LAST block of each group except the final group, matching the reference's
    ``stack2`` layout."""

    stage_sizes: Sequence[int]
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        # keras-applications pads explicitly (ZeroPadding2D 3 then VALID,
        # pool pad 1) — matched for HDF5-import activation parity
        x = nn.Conv(64, (7, 7), strides=(2, 2), padding=((3, 3), (3, 3)),
                    use_bias=True,
                    kernel_init=he_normal, dtype=self.dtype, name="stem")(x)
        x = layers.max_pool(x, (3, 3), (2, 2), padding=((1, 1), (1, 1)))
        n_stages = len(self.stage_sizes)
        for i, n_blocks in enumerate(self.stage_sizes):
            feats = 64 * (2 ** i)
            for j in range(n_blocks):
                last = j == n_blocks - 1
                strides = 2 if (last and i < n_stages - 1) else 1
                x = PreActBottleneck(
                    feats, strides=strides, project=(j == 0),
                    dtype=self.dtype, name=f"stage{i + 1}_block{j + 1}",
                )(x, train)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1.001e-5, dtype=jnp.float32, name="post_bn")(x)
        x = nn.relu(x)
        x = layers.global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32,
                        kernel_init=he_normal, name="fc")(x)


@register("resnet34")
def _resnet34(**kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BasicBlock, **kw)


@register("resnet50")
def _resnet50(**kw):
    return ResNet(stage_sizes=(3, 4, 6, 3), block=BottleneckBlock, **kw)


@register("resnet152", remat="block")
def _resnet152(**kw):
    # block-boundary remat declared as the registry default (ISSUE 15):
    # at 36 stage-3 blocks the saved-activation surface dominates the
    # step's HBM; recompute inside each block trades MXU headroom for it
    return ResNet(stage_sizes=(3, 8, 36, 3), block=BottleneckBlock, **kw)


@register("resnet50v2")
def _resnet50v2(**kw):
    return ResNetV2(stage_sizes=(3, 4, 6, 3), **kw)
