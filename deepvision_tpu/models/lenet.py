"""LeNet-5 for MNIST.

Capability parity with both reference variants:
- PyTorch: tanh activations, average pooling, softmax head replacing the
  paper's RBF output (ref: LeNet/pytorch/models/lenet5.py:8-67).
- TF/Keras: sigmoid between pools (ref: LeNet/tensorflow/models/lenet5.py:7-34)
  — selectable via ``activation="sigmoid"``.

Input is a 32x32x1 image (MNIST 28x28 padded to 32 by the data pipeline, as
the reference's loader does — ref: LeNet/pytorch/data_load.py:12-57).
Outputs raw logits; softmax lives in the loss/eval code.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.registry import register


class LeNet5(nn.Module):
    num_classes: int = 10
    activation: str = "tanh"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        act = {"tanh": nn.tanh, "sigmoid": nn.sigmoid}[self.activation]
        conv = lambda f, name: nn.Conv(
            f, (5, 5), padding="VALID", dtype=self.dtype, name=name
        )
        x = x.astype(self.dtype)
        x = act(conv(6, "c1")(x))            # 32 -> 28
        x = layers.avg_pool(x)               # 28 -> 14
        x = act(conv(16, "c3")(x))           # 14 -> 10
        x = layers.avg_pool(x)               # 10 -> 5
        x = act(conv(120, "c5")(x))          # 5 -> 1
        x = x.reshape((x.shape[0], -1))
        x = act(nn.Dense(84, dtype=self.dtype, name="f6")(x))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="output")(x)
        return x


@register("lenet5")
def _lenet5(**kw) -> LeNet5:
    return LeNet5(**kw)


@register("lenet5_tf")
def _lenet5_tf(**kw) -> LeNet5:
    kw.setdefault("activation", "sigmoid")
    return LeNet5(**kw)
