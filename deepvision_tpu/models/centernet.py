"""CenterNet / ObjectsAsPoints detector (Flax, NHWC).

Capability parity with ref: ObjectsAsPoints/tensorflow/model.py:17-179 —
2-stack "large hourglass" (order-5 recursion with per-order filter/depth
maps) and a 3-branch detection head per stack (class center heatmap,
box wh, center offset). The reference left this component unfinished
(trainer inert, ref train.py:35,248); this is the completed capability.

Reference defects fixed rather than copied (SURVEY "known defects"):

- ref model.py:119-121 — the ``low3`` residual loop's result is discarded
  (the final block reads ``low2``). We apply the trailing blocks
  sequentially per the CenterNet source the ref cites
  (large_hourglass.py kp_module).
- ref model.py:176 — ``intermediate = ResidualBlock(x, …)`` throws away
  the computed 2-conv re-injection sum. We feed the sum through the
  residual block, per the cited source (large_hourglass.py:220-225).

Divergence for trainability: the class-heatmap output conv's bias is
initialized to −2.19 (prior prob ≈ 0.1) per the CenterNet/CornerNet
recipe — the reference never trained, so it has no working init to
mirror; without it penalty-reduced focal loss starts unstable.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepvision_tpu.models.layers import he_normal
from deepvision_tpu.models.registry import register

Dtype = Any

# Per-order (filters at this order, filters one level down) and residual
# depths — ref: model.py:17-32 (from CenterNet large_hourglass).
ORDER_FILTERS = {5: (256, 256), 4: (256, 384), 3: (384, 384),
                 2: (384, 384), 1: (384, 512)}
ORDER_RESIDUAL = {5: (2, 2), 4: (2, 2), 3: (2, 2), 2: (2, 2), 1: (2, 4)}


class ResidualBlock(nn.Module):
    """Post-activation residual: 1x1/s → BN → ReLU → 3x3 → BN, + skip
    (1x1+BN projection on channel/stride change), ReLU (ref: model.py:35-69).
    """

    features: int
    strides: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f, d = self.features, self.dtype

        def bn(x, name):
            # MixedBatchNorm: f32 statistics, compute-dtype apply —
            # the ISSUE 15 recipe (f32 stats pins removed zoo-wide)
            from deepvision_tpu.models.layers import MixedBatchNorm

            return MixedBatchNorm(use_running_average=not train,
                                  dtype=d, name=name)(x)

        identity = x
        if x.shape[-1] != f or self.strides > 1:
            identity = nn.Conv(f, (1, 1), strides=(self.strides,) * 2,
                               use_bias=False, kernel_init=he_normal,
                               dtype=d, name="proj")(x)
            identity = bn(identity, "proj_bn")
        y = nn.Conv(f, (1, 1), strides=(self.strides,) * 2, use_bias=False,
                    kernel_init=he_normal, dtype=d, name="conv1")(x)
        y = nn.relu(bn(y, "bn1"))
        y = nn.Conv(f, (3, 3), use_bias=False, kernel_init=he_normal,
                    dtype=d, name="conv2")(y)
        y = bn(y, "bn2")
        # f32 residual CARRIER through the 2-stack order-5 recursion —
        # same structural guard as models/hourglass.py (no-op at f32)
        hd = jnp.promote_types(d, jnp.float32)
        return nn.relu(identity.astype(hd) + y.astype(hd))


class LargeHourglass(nn.Module):
    """Order-``order`` module with per-order widths (ref: model.py:94-127)."""

    order: int
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        curr_f, next_f = ORDER_FILTERS[self.order]
        curr_r, next_r = ORDER_RESIDUAL[self.order]

        up = x
        for i in range(curr_r):
            up = ResidualBlock(curr_f, dtype=d, name=f"up{i}")(up, train)

        low = ResidualBlock(next_f, strides=2, dtype=d,
                            name="down")(x, train)
        for i in range(curr_r - 1):
            low = ResidualBlock(next_f, dtype=d,
                                name=f"low1_{i}")(low, train)
        if self.order > 1:
            low = LargeHourglass(self.order - 1, dtype=d,
                                 name=f"inner{self.order - 1}")(low, train)
        else:
            for i in range(next_r):
                low = ResidualBlock(next_f, dtype=d,
                                    name=f"bottom_{i}")(low, train)
        # trailing blocks applied sequentially (ref defect at :119-121)
        for i in range(curr_r - 1):
            low = ResidualBlock(next_f, dtype=d,
                                name=f"low3_{i}")(low, train)
        low = ResidualBlock(curr_f, dtype=d, name="low3_out")(low, train)

        b, h, w, c = low.shape
        up2 = jax.image.resize(low, (b, 2 * h, 2 * w, c), method="nearest")
        return up + up2


class DetectionBranch(nn.Module):
    """3x3(256)+ReLU → 3x3(out); no BN (ref: model.py:72-78)."""

    out_features: int
    bias_init_value: float = 0.0
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(256, (3, 3), use_bias=True, kernel_init=he_normal,
                    dtype=self.dtype, name="conv1")(x)
        y = nn.relu(y)
        return nn.Conv(
            self.out_features, (3, 3), use_bias=True,
            kernel_init=he_normal,
            bias_init=nn.initializers.constant(self.bias_init_value),
            dtype=jnp.float32, name="out",
        )(y.astype(jnp.float32))


class CenterNet(nn.Module):
    """2-stack large hourglass; per stack returns (heatmap logits (B,H,W,C),
    wh (B,H,W,2), offset (B,H,W,2)) at output stride 4."""

    num_classes: int = 80
    num_stacks: int = 2
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype

        def bn(x, name):
            from deepvision_tpu.models.layers import MixedBatchNorm

            return MixedBatchNorm(use_running_average=not train,
                                  dtype=d, name=name)(x)

        # Stem (ref: model.py:140-145): 7x7/2 128 → residual 256 /2.
        x = nn.Conv(128, (7, 7), strides=(2, 2), use_bias=False,
                    kernel_init=he_normal, dtype=d, name="stem_conv")(x)
        x = nn.relu(bn(x, "stem_bn"))
        inter = ResidualBlock(256, strides=2, dtype=d,
                              name="stem_res")(x, train)

        outputs = []
        for s in range(self.num_stacks):
            y = LargeHourglass(5, dtype=d, name=f"hg{s}")(inter, train)
            y = nn.Conv(256, (3, 3), use_bias=True, kernel_init=he_normal,
                        dtype=d, name=f"post{s}_conv")(y)
            y = nn.relu(bn(y, f"post{s}_bn"))

            heat = DetectionBranch(self.num_classes, bias_init_value=-2.19,
                                   dtype=d, name=f"head{s}_heat")(y)
            wh = DetectionBranch(2, dtype=d, name=f"head{s}_wh")(y)
            off = DetectionBranch(2, dtype=d, name=f"head{s}_off")(y)
            outputs.append((heat, wh, off))

            if s < self.num_stacks - 1:
                x1 = nn.Conv(256, (1, 1), use_bias=True, dtype=d,
                             name=f"remap_feat{s}")(y)
                x1 = bn(x1, f"remap_feat{s}_bn")
                x2 = nn.Conv(256, (1, 1), use_bias=True, dtype=d,
                             name=f"remap_prev{s}")(inter)
                x2 = bn(x2, f"remap_prev{s}_bn")
                # cross-stack carrier stays f32 (no-op at f32)
                hd = jnp.promote_types(d, jnp.float32)
                inter = nn.relu(x1.astype(hd) + x2.astype(hd))
                # re-injection passes THROUGH the residual (ref defect :176)
                inter = ResidualBlock(256, dtype=d,
                                      name=f"remap_res{s}")(inter, train)
        return tuple(outputs)


@register("centernet")
def centernet(num_classes: int = 80, dtype: Dtype = jnp.float32,
              **kw) -> CenterNet:
    return CenterNet(num_classes=num_classes, dtype=dtype, **kw)
