"""Stacked Hourglass network for human pose estimation (Flax, NHWC).

Capability parity with ref: Hourglass/tensorflow/hourglass104.py:19-159 —
pre-activation bottleneck residuals, order-4 recursive hourglass modules,
4 stacks with intermediate supervision heads (one heatmap tensor per stack),
and the 1/4-resolution stem (256² input → 64² features).

Deliberate divergences from the reference (documented, not copied):

- ref bug: the stack loop shadows its index with the inner residual loop's
  variable, so the "not the last stack" re-injection test reads the wrong
  ``i`` (hourglass104.py:136-157) and the last stack builds re-injection
  convs whose output is dropped. We use the real stack index: intermediate
  predictions are re-injected after every stack except the last, per the
  paper.
- the hourglass recursion is unrolled in Python at trace time (static
  ``order``), producing one fused XLA computation — no Keras graph
  assembly.

The recursion and block structure follow the paper (Newell et al. 2016)
semantics the reference implements: upper branch residuals at full
resolution, lower branch maxpool → residuals → recurse → residuals →
nearest-neighbor ×2 upsample, summed.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from deepvision_tpu.models.layers import he_normal, max_pool
from deepvision_tpu.models.registry import register
from deepvision_tpu.parallel.constraint import guard_thin_h

Dtype = Any


class PreActBottleneck(nn.Module):
    """BN→ReLU→1x1(f/2) → BN→ReLU→3x3(f/2) → BN→ReLU→1x1(f), + identity.

    Matches the ref's Residual.lua-derived block (hourglass104.py:19-67):
    pre-activation ordering with a *linear* 1x1 projection on the skip when
    the channel count changes.
    """

    features: int
    project: bool = False  # 1x1-project the skip (ref ``downsample``)
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f, d = self.features, self.dtype
        identity = x
        if self.project:
            identity = nn.Conv(f, (1, 1), use_bias=True,
                               kernel_init=he_normal, dtype=d,
                               name="proj")(x)

        def bn(x, name):
            # MixedBatchNorm: statistics always f32; the elementwise
            # apply runs in the block's compute dtype. The r4
            # bf16-cripples-hourglass finding is addressed structurally
            # instead of by a dtype pin: the cross-stack/residual
            # CARRIER stays f32 (StackedHourglass), so bf16 rounding no
            # longer compounds through the recursive depth.
            from deepvision_tpu.models.layers import MixedBatchNorm

            return MixedBatchNorm(use_running_average=not train,
                                  momentum=0.9, dtype=d, name=name)(x)

        y = nn.relu(bn(x, "bn1"))
        y = nn.Conv(f // 2, (1, 1), use_bias=True, kernel_init=he_normal,
                    dtype=d, name="conv1")(y)
        y = nn.relu(bn(y, "bn2"))
        y = nn.Conv(f // 2, (3, 3), use_bias=True, kernel_init=he_normal,
                    dtype=d, name="conv2")(y)
        y = nn.relu(bn(y, "bn3"))
        y = nn.Conv(f, (1, 1), use_bias=True, kernel_init=he_normal,
                    dtype=d, name="conv3")(y)
        # f32 residual CARRIER (precision floor, not ceiling): block
        # internals compute in ``dtype``, but the skip sum accumulates
        # in promoted f32 so rounding cannot compound through the
        # order-4 recursion x 4 stacks — the structural fix for the r4
        # bf16-cripples-hourglass finding. Identity at f32 dtype.
        hd = jnp.promote_types(d, jnp.float32)
        return identity.astype(hd) + y.astype(hd)


def _upsample2x(x):
    """Nearest-neighbor ×2 (ref UpSampling2D, hourglass104.py:96)."""
    b, h, w, c = x.shape
    return jax.image.resize(x, (b, 2 * h, 2 * w, c), method="nearest")


class HourglassModule(nn.Module):
    """Order-``order`` recursive hourglass (ref: hourglass104.py:70-98)."""

    order: int
    features: int = 256
    num_residual: int = 1
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f, d, r = self.features, self.dtype, self.num_residual
        # Upper branch: 1 + num_residual blocks at this resolution.
        up = PreActBottleneck(f, dtype=d, name="up0")(x, train)
        for i in range(r):
            up = PreActBottleneck(f, dtype=d, name=f"up{i + 1}")(up, train)
        # Lower branch. Under spatial partitioning the recursion pools
        # H down to single rows; drop the H sharding once shards thin
        # below the safe bound (parallel/constraint.py — the XLA SPMD
        # thin-shard backward bug; no-op outside a spatial mesh).
        low = guard_thin_h(max_pool(x))
        for i in range(r):
            low = PreActBottleneck(f, dtype=d, name=f"low1_{i}")(low, train)
        if self.order > 1:
            low = HourglassModule(self.order - 1, f, r, dtype=d,
                                  name=f"inner{self.order - 1}")(low, train)
        else:
            for i in range(r):
                low = PreActBottleneck(f, dtype=d,
                                       name=f"bottom_{i}")(low, train)
        for i in range(r):
            low = PreActBottleneck(f, dtype=d, name=f"low3_{i}")(low, train)
        return up + _upsample2x(low)


class StackedHourglass(nn.Module):
    """4-stack hourglass returning one (B, 64, 64, K) heatmap per stack.

    All stack outputs are supervised during training (intermediate
    supervision); inference uses the last. Heads are f32 regardless of the
    compute dtype.
    """

    num_stacks: int = 4
    num_residual: int = 1
    num_heatmaps: int = 16
    features: int = 256
    # activation rematerialization (HBM-traffic/memory lever; the
    # parameter pytree is unchanged — nn.remat is a lifted transform
    # preserving module names):
    #   None    — save what XLA saves (default)
    #   "stack" — save only stack boundaries; each of the 4 hourglass
    #             modules recomputes its order-4 recursion during
    #             backward (the deepest activation surface in the zoo)
    remat: str | None = None
    dtype: Dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f, d = self.features, self.dtype
        if self.remat not in (None, "stack"):
            raise ValueError(
                f"unknown hourglass remat policy {self.remat!r} "
                "(None or 'stack')")
        hg_cls = HourglassModule
        if self.remat == "stack":
            # prevent_cse=True (the jax.checkpoint default): stacks are
            # unrolled, not scanned — without the optimization barriers
            # XLA's CSE undoes the recompute (same finding as
            # models/resnet.ResNet.remat)
            hg_cls = nn.remat(HourglassModule, prevent_cse=True,
                              static_argnums=(2,))

        hd = jnp.promote_types(d, jnp.float32)  # f32 floor, not ceiling

        def bn(x, name):
            from deepvision_tpu.models.layers import MixedBatchNorm

            # stem/linear BNs: f32 statistics, compute-dtype apply —
            # same mixed recipe as the block BNs (layers.MixedBatchNorm)
            return MixedBatchNorm(use_running_average=not train,
                                  momentum=0.9, dtype=d, name=name)(x)

        # Stem: 7x7/2 → bottleneck(128, proj) → pool → ×2 bottleneck → 256.
        # (ref: hourglass104.py:121-133; 256² → 64²)
        x = nn.Conv(64, (7, 7), strides=(2, 2), use_bias=True,
                    kernel_init=he_normal, dtype=d, name="stem_conv")(x)
        x = nn.relu(bn(x, "stem_bn"))
        x = PreActBottleneck(128, project=True, dtype=d,
                             name="stem_res1")(x, train)
        x = max_pool(x)
        x = PreActBottleneck(128, dtype=d, name="stem_res2")(x, train)
        x = PreActBottleneck(f, project=True, dtype=d,
                             name="stem_res3")(x, train)

        outputs = []
        for s in range(self.num_stacks):
            y = hg_cls(4, f, self.num_residual, dtype=d,
                       name=f"hg{s}")(x, train)
            for i in range(self.num_residual):
                y = PreActBottleneck(f, dtype=d,
                                     name=f"post{s}_{i}")(y, train)
            # "Linear layer": 1x1 conv-BN-ReLU (ref: hourglass104.py:101-110).
            y = nn.Conv(f, (1, 1), use_bias=True, kernel_init=he_normal,
                        dtype=d, name=f"linear{s}_conv")(y)
            y = nn.relu(bn(y, f"linear{s}_bn"))
            heat = nn.Conv(self.num_heatmaps, (1, 1), use_bias=True,
                           kernel_init=he_normal, dtype=hd,
                           name=f"head{s}")(y.astype(hd))
            outputs.append(heat)
            if s < self.num_stacks - 1:  # the ref's shadowed-index fix
                # Paper/hg.lua re-injection is a 3-term sum (previous stack
                # input + remapped features + remapped prediction); the ref
                # drops the first term (hourglass104.py:155-157) — we keep it.
                re_x = nn.Conv(f, (1, 1), use_bias=True, dtype=d,
                               name=f"remap_feat{s}")(y)
                re_y = nn.Conv(f, (1, 1), use_bias=True, dtype=d,
                               name=f"remap_pred{s}")(heat.astype(d))
                x = x + re_x + re_y
        return tuple(outputs)


@register("hourglass104", remat="stack")
def hourglass104(num_heatmaps: int = 16, dtype: Dtype = jnp.float32,
                 **kw) -> StackedHourglass:
    """The MPII configuration: 4 stacks, 1 residual, 16 joints
    (ref: Hourglass/tensorflow/train.py:211). Per-stack remat is the
    registry-declared policy (ISSUE 15): the order-4 recursion x 4
    stacks is the deepest activation surface in the zoo."""
    return StackedHourglass(num_stacks=4, num_residual=1,
                            num_heatmaps=num_heatmaps, dtype=dtype, **kw)
