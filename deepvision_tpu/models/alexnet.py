"""AlexNet V1 and V2.

- V1: the original 2012 net collapsed into a single tower with the paper's
  per-tower channel counts doubled, LRN after conv1/conv2, overlapping
  3x3/2 max-pools, dropout(0.5) on both hidden FC layers —
  ref: AlexNet/pytorch/models/alexnet_v1.py:11-125.
- V2: the "one weird trick" single-column variant (64/192/384/384/256), no
  LRN — ref: AlexNet/pytorch/models/alexnet_v2.py:12-75. The TF twin pads
  input to 227 and keeps an LRN Layer —
  ref: AlexNet/tensorflow/models/alexnet_v2.py:9-70; its LRN is available
  here via ``use_lrn=True``.

Inputs are 224x224x3 (V1 uses VALID 11x11/4 conv ≈ the paper's 227 geometry).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.registry import register
from deepvision_tpu.ops.lrn import local_response_norm


class AlexNetV1(nn.Module):
    num_classes: int = 1000
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=p, dtype=self.dtype, name=name
        )
        # conv1: 96 filters 11x11/4 + LRN + pool (channel counts are the
        # doubled single-tower numbers, ref: alexnet_v1.py:13 note).
        # Asymmetric (1,2) padding makes 224 behave as the paper's 227,
        # giving the 6x6x256 flatten the 60M-param FC stack requires
        # (the TF twin zero-pads to 227 — ref: alexnet_v2.py ZeroPadding).
        x = nn.relu(conv(96, 11, 4, [(1, 2), (1, 2)], "conv1")(x))
        x = local_response_norm(x)
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(256, 5, 1, "SAME", "conv2")(x))
        x = local_response_norm(x)
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(384, 3, 1, "SAME", "conv3")(x))
        x = nn.relu(conv(384, 3, 1, "SAME", "conv4")(x))
        x = nn.relu(conv(256, 3, 1, "SAME", "conv5")(x))
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc7")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc8")(x)


class AlexNetV2(nn.Module):
    num_classes: int = 1000
    use_lrn: bool = False  # TF variant keeps LRN (alexnet_v2.py:9-24)
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        conv = lambda f, k, s, p, name: nn.Conv(
            f, (k, k), strides=(s, s), padding=p, dtype=self.dtype, name=name
        )
        x = nn.relu(conv(64, 11, 4, [(2, 2), (2, 2)], "conv1")(x))
        if self.use_lrn:
            x = local_response_norm(x)
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(192, 5, 1, "SAME", "conv2")(x))
        if self.use_lrn:
            x = local_response_norm(x)
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(384, 3, 1, "SAME", "conv3")(x))
        x = nn.relu(conv(384, 3, 1, "SAME", "conv4")(x))
        x = nn.relu(conv(256, 3, 1, "SAME", "conv5")(x))
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc6")(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype, name="fc7")(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc8")(x)


@register("alexnet1")
def _alexnet_v1(**kw):
    return AlexNetV1(**kw)


@register("alexnet2")
def _alexnet_v2(**kw):
    return AlexNetV2(**kw)


@register("alexnet2_tf")
def _alexnet_v2_tf(**kw):
    kw.setdefault("use_lrn", True)
    return AlexNetV2(**kw)
