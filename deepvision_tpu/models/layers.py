"""Shared building blocks for the model zoo (NHWC, Flax linen).

Layout is NHWC throughout — the TPU-native convolution layout — whereas the
PyTorch reference is NCHW; the checkpoint converter (convert/torch_import.py)
owns the transpose. Weight init helpers mirror the reference's documented
choices (he-normal convs + BN gamma=1/beta=0 for ResNet —
ref: ResNet/pytorch/models/resnet50.py:84-93; xavier convs for VGG —
ref: VGG/pytorch/models/vgg16.py:113-119).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

Dtype = Any

he_normal = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")
xavier_uniform = nn.initializers.xavier_uniform()


class MixedBatchNorm(nn.BatchNorm):
    """BatchNorm with f32 statistics but COMPUTE-dtype normalize math —
    the mixed-precision BN the HBM diet standardizes on.

    Stock linen (``force_float32_reductions``, the right default for the
    statistics) also computes the elementwise normalize in f32: with
    ``dtype=bf16`` the ``x - mean`` promotes the whole activation to f32
    and every BN materializes full-size f32 intermediates — exactly the
    f32 surface ``make bf16-ready`` showed dominating the deep models'
    jaxprs (6 GB on ResNet-152 b4). Here the running statistics, their
    momentum updates and the per-channel affine stay f32, but the
    full-size elementwise apply is ONE compute-dtype multiply-add
    (``x * mul + shift`` with the f32 channel affine folded and cast
    once — the standard fused-BN-apply form, better bf16 rounding than
    the unfused ``(x - mean) * mul + bias`` chain). At f32 compute dtype
    the stock expression tree is used bit-for-bit, so converter-parity
    configs are unaffected. Parameter/variable names and dtypes are
    identical to ``nn.BatchNorm`` (checkpoints, the torch converter and
    the batch_stats pytree see no difference).
    """

    @nn.compact
    def __call__(self, x, use_running_average: bool | None = None, *,
                 mask=None):
        from flax.linen import normalization as N
        from flax.linen.module import merge_param

        use_running_average = merge_param(
            "use_running_average", self.use_running_average,
            use_running_average)
        feature_axes = N._canonicalize_axes(x.ndim, self.axis)
        reduction_axes = tuple(i for i in range(x.ndim)
                               if i not in feature_axes)
        feature_shape = [x.shape[ax] for ax in feature_axes]

        ra_mean = self.variable("batch_stats", "mean",
                                lambda s: jnp.zeros(s, jnp.float32),
                                feature_shape)
        ra_var = self.variable("batch_stats", "var",
                               lambda s: jnp.ones(s, jnp.float32),
                               feature_shape)
        import numpy as _np

        # STATIC config predicate (module fields only — never data):
        # picks the trace, does not branch on traced values
        mixed = (self.dtype is not None
                 and _np.dtype(self.dtype) != _np.dtype("float32")
                 and self.axis_name is None)
        if mask is not None:
            mixed = False  # masked stats: defer to stock _compute_stats
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        elif mixed:
            # mixed statistics: moments taken on the COMPUTE-dtype
            # tensors with f32 accumulators (jnp.mean dtype=f32 —
            # convert fuses into the reduce), so no full-size f32
            # copy/square ever materializes. Stock linen upcasts x to
            # f32 first: that f32 activation copy + its f32 square per
            # BN are exactly the surviving f32 surface `make
            # bf16-ready` measured dominating the deep models' jaxprs.
            # The bf16-rounded moments perturb var by ~2^-8 relative —
            # noise well under batch-statistics noise; the accumulators
            # and the channel math stay f32 (no cancellation change vs
            # stock's use_fast_variance, which also does E[x²]-E[x]²).
            xc = x.astype(self.dtype)
            mean = jnp.mean(xc, reduction_axes, dtype=jnp.float32)
            if self.use_fast_variance:
                mean2 = jnp.mean(lax.square(xc), reduction_axes,
                                 dtype=jnp.float32)
                var = jnp.maximum(mean2 - lax.square(mean), 0.0)
            else:
                # two-pass (use_fast_variance=False is chosen exactly
                # for large-mean activations where E[x²]-E[x]² cancels)
                d = xc - jnp.expand_dims(mean, reduction_axes).astype(
                    xc.dtype)
                var = jnp.mean(lax.square(d), reduction_axes,
                               dtype=jnp.float32)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)
        else:
            mean, var = N._compute_stats(
                x, reduction_axes, dtype=self.dtype,
                axis_name=(self.axis_name
                           if not self.is_initializing() else None),
                axis_index_groups=self.axis_index_groups,
                use_fast_variance=self.use_fast_variance, mask=mask,
                force_float32_reductions=True,
            )
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)

        # per-channel affine in f32 (same param creation order as stock
        # _normalize: scale then bias — identical pytree)
        bshape = [1] * x.ndim
        for ax in feature_axes:
            bshape[ax] = x.shape[ax]
        mean = jnp.expand_dims(mean, reduction_axes).astype(jnp.float32)
        var = jnp.expand_dims(var, reduction_axes).astype(jnp.float32)
        mul = lax.rsqrt(var + self.epsilon)
        scale = bias = None
        if self.use_scale:
            scale = self.param("scale", self.scale_init, feature_shape,
                               self.param_dtype).reshape(bshape)
            mul = mul * scale
        if self.use_bias:
            bias = self.param("bias", self.bias_init, feature_shape,
                              self.param_dtype).reshape(bshape)

        # same result-dtype rule as stock _normalize: an explicit
        # ``dtype`` wins; otherwise promote input and param dtypes
        from flax.linen import dtypes as flax_dtypes

        args = [x] + [a for a in (scale, bias) if a is not None]
        out_dtype = flax_dtypes.canonicalize_dtype(*args,
                                                   dtype=self.dtype)
        if out_dtype == jnp.float32:
            # stock expression tree, bit-for-bit (converter parity)
            y = (x.astype(jnp.float32) - mean) * mul
            if bias is not None:
                y = y + bias
            return jnp.asarray(y, out_dtype)
        # mixed apply: fold the channel affine in f32, cast ONCE, run
        # the full-size elementwise in the compute dtype
        shift = -mean * mul
        if bias is not None:
            shift = shift + bias
        return (x.astype(out_dtype) * mul.astype(out_dtype)
                + shift.astype(out_dtype))


class ConvBN(nn.Module):
    """Conv → BatchNorm → optional activation, the zoo's workhorse block.

    BN statistics are kept in f32 regardless of compute dtype (linen's
    ``force_float32_reductions`` default), but the normalize/scale/shift
    elementwise math runs in the model's compute dtype: pinning it to f32
    made XLA materialize every post-BN activation twice per step (an f32
    write + a bf16 convert write — profiler-measured 94GB of HBM traffic
    per ResNet-50 batch-256 step, HBM-bound at MFU 0.22). ``use_running``
    follows linen's ``use_running_average`` convention and is threaded via
    the ``train`` argument of the parent model.
    """

    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence = "SAME"
    groups: int = 1
    use_bias: bool = False
    act: Callable | None = nn.relu
    kernel_init: Callable = he_normal
    dtype: Dtype = jnp.float32
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            dtype=self.dtype,
            name="conv",
        )(x)
        # named for remat policies (ResNet.remat="conv"): lets backward
        # keep only conv outputs and recompute the cheap BN/ReLU
        # elementwise chain fused into its consumers, instead of
        # re-reading separately saved post-BN activations from HBM.
        # A plain no-op identity outside any remat scope.
        x = checkpoint_name(x, "conv_out")
        x = MixedBatchNorm(
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            dtype=self.dtype,
            name="bn",
        )(x)
        if self.act is not None:
            x = self.act(x)
        return x


def max_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.max_pool(x, window, strides or window, padding)


def avg_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.avg_pool(x, window, strides or window, padding)


def global_avg_pool(x):
    """GAP over H, W — NHWC (B, H, W, C) -> (B, C), f32 accumulation."""
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2)).astype(x.dtype)
