"""Shared building blocks for the model zoo (NHWC, Flax linen).

Layout is NHWC throughout — the TPU-native convolution layout — whereas the
PyTorch reference is NCHW; the checkpoint converter (convert/torch_import.py)
owns the transpose. Weight init helpers mirror the reference's documented
choices (he-normal convs + BN gamma=1/beta=0 for ResNet —
ref: ResNet/pytorch/models/resnet50.py:84-93; xavier convs for VGG —
ref: VGG/pytorch/models/vgg16.py:113-119).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

Dtype = Any

he_normal = nn.initializers.variance_scaling(2.0, "fan_out", "truncated_normal")
xavier_uniform = nn.initializers.xavier_uniform()


class ConvBN(nn.Module):
    """Conv → BatchNorm → optional activation, the zoo's workhorse block.

    BN statistics are kept in f32 regardless of compute dtype (linen's
    ``force_float32_reductions`` default), but the normalize/scale/shift
    elementwise math runs in the model's compute dtype: pinning it to f32
    made XLA materialize every post-BN activation twice per step (an f32
    write + a bf16 convert write — profiler-measured 94GB of HBM traffic
    per ResNet-50 batch-256 step, HBM-bound at MFU 0.22). ``use_running``
    follows linen's ``use_running_average`` convention and is threaded via
    the ``train`` argument of the parent model.
    """

    features: int
    kernel: tuple[int, int] = (3, 3)
    strides: tuple[int, int] = (1, 1)
    padding: str | Sequence = "SAME"
    groups: int = 1
    use_bias: bool = False
    act: Callable | None = nn.relu
    kernel_init: Callable = he_normal
    dtype: Dtype = jnp.float32
    bn_momentum: float = 0.9
    bn_epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(
            self.features,
            self.kernel,
            strides=self.strides,
            padding=self.padding,
            feature_group_count=self.groups,
            use_bias=self.use_bias,
            kernel_init=self.kernel_init,
            dtype=self.dtype,
            name="conv",
        )(x)
        # named for remat policies (ResNet.remat="conv"): lets backward
        # keep only conv outputs and recompute the cheap BN/ReLU
        # elementwise chain fused into its consumers, instead of
        # re-reading separately saved post-BN activations from HBM.
        # A plain no-op identity outside any remat scope.
        x = checkpoint_name(x, "conv_out")
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=self.bn_epsilon,
            dtype=self.dtype,
            name="bn",
        )(x)
        if self.act is not None:
            x = self.act(x)
        return x


def max_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.max_pool(x, window, strides or window, padding)


def avg_pool(x, window=(2, 2), strides=None, padding="VALID"):
    return nn.avg_pool(x, window, strides or window, padding)


def global_avg_pool(x):
    """GAP over H, W — NHWC (B, H, W, C) -> (B, C), f32 accumulation."""
    return jnp.mean(x.astype(jnp.float32), axis=(1, 2)).astype(x.dtype)
