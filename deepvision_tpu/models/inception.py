"""Inception V1 (GoogLeNet) and Inception V3.

V1 re-expresses ref: Inception/pytorch/models/inception_v1.py:9-201 — 9
inception modules, two auxiliary classifiers that are active only in
training (ref: inception_v1.py:92-99,112-113; the train step weights them
0.3, see train/steps.py).

V3: the reference file is a 6-line stub (ref:
Inception/pytorch/models/inception_v3.py:1-6 — imports + paper link only).
Implemented here in full per the paper ("Rethinking the Inception
Architecture", factorized 7x7 / asymmetric convs, one aux head), i.e. this
is a deliberate CAPABILITY COMPLETION beyond the reference — divergence
flagged per SURVEY §2.1.
"""

from __future__ import annotations

from typing import Sequence

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.layers import ConvBN
from deepvision_tpu.models.registry import register
from deepvision_tpu.ops.lrn import local_response_norm


class BasicConv(nn.Module):
    """conv(+bias)+ReLU — the reference's ``BasicConv2d`` exactly (NO
    BatchNorm, ref: Inception/pytorch/models/inception_v1.py:193-200).
    Converter-parity twin of ConvBN; child named ``conv`` so torch keys
    map onto the same path shape."""

    features: int
    kernel: tuple[int, int] = (1, 1)
    strides: tuple[int, int] = (1, 1)
    padding: str | tuple = "SAME"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        del train
        x = nn.Conv(self.features, self.kernel, strides=self.strides,
                    padding=self.padding, use_bias=True, dtype=self.dtype,
                    name="conv")(x)
        return nn.relu(x)


class InceptionModule(nn.Module):
    """4-branch module: 1x1 | 1x1→3x3 | 1x1→5x5 | pool→1x1.

    ``bn=True`` (default) is the BN-modernized variant this framework
    trains; ``bn=False`` reproduces the reference's conv+bias+ReLU blocks
    for checkpoint-converter logits parity."""

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int
    dtype: jnp.dtype = jnp.float32
    bn: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        conv = ConvBN if self.bn else BasicConv
        b1 = conv(self.c1, (1, 1), dtype=d, name="b1")(x, train)
        b3 = conv(self.c3r, (1, 1), dtype=d, name="b3r")(x, train)
        b3 = conv(self.c3, (3, 3), dtype=d, name="b3")(b3, train)
        b5 = conv(self.c5r, (1, 1), dtype=d, name="b5r")(x, train)
        b5 = conv(self.c5, (5, 5), dtype=d, name="b5")(b5, train)
        bp = layers.max_pool(x, (3, 3), (1, 1), padding="SAME")
        bp = conv(self.cp, (1, 1), dtype=d, name="bp")(bp, train)
        return jnp.concatenate([b1, b3, b5, bp], axis=-1)


class AuxiliaryClassifier(nn.Module):
    """avgpool5/3 → 1x1(128) → fc1024 → dropout(0.7) → fc — active only in
    training (ref: inception_v1.py:92-99)."""

    num_classes: int
    dtype: jnp.dtype = jnp.float32
    bn: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = ConvBN if self.bn else BasicConv
        x = layers.avg_pool(x, (5, 5), (3, 3))
        x = conv(128, (1, 1), dtype=self.dtype, name="proj")(x, train)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype, name="fc1")(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc2")(x)


class InceptionV1(nn.Module):
    """``bn=True`` (default): the BN-modernized training variant.
    ``bn=False``: the reference's exact architecture — conv+bias+ReLU
    blocks, stem LRNs after pool1/conv3x3, torch-symmetric stem padding —
    for converter logits parity
    (ref: Inception/pytorch/models/inception_v1.py:27-113)."""

    num_classes: int = 1000
    aux_heads: bool = True
    dtype: jnp.dtype = jnp.float32
    bn: bool = True

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        conv = ConvBN if self.bn else BasicConv
        x = x.astype(d)
        # torch pads the 7x7/2 stem (3,3); XLA "SAME" would pad (2,3)
        x = conv(64, (7, 7), (2, 2),
                 padding="SAME" if self.bn else ((3, 3), (3, 3)),
                 dtype=d, name="stem1")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2), padding="SAME")
        if not self.bn:  # ref: inception_v1.py:30,82 — torch LRN defaults
            x = local_response_norm(x, size=64, alpha=1e-4, beta=0.75, k=1.0)
        x = conv(64, (1, 1), dtype=d, name="stem2")(x, train)
        x = conv(192, (3, 3), dtype=d, name="stem3")(x, train)
        if not self.bn:  # ref: inception_v1.py:38,84 — LRN window = 192 chans
            x = local_response_norm(x, size=192, alpha=1e-4, beta=0.75, k=1.0)
        x = layers.max_pool(x, (3, 3), (2, 2), padding="SAME")

        mod = lambda *c, name: InceptionModule(*c, dtype=d, bn=self.bn,
                                               name=name)
        x = mod(64, 96, 128, 16, 32, 32, name="i3a")(x, train)
        x = mod(128, 128, 192, 32, 96, 64, name="i3b")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = mod(192, 96, 208, 16, 48, 64, name="i4a")(x, train)
        aux1 = None
        if self.aux_heads and train:
            aux1 = AuxiliaryClassifier(self.num_classes, dtype=d,
                                       bn=self.bn, name="aux1")(x, train)
        x = mod(160, 112, 224, 24, 64, 64, name="i4b")(x, train)
        x = mod(128, 128, 256, 24, 64, 64, name="i4c")(x, train)
        x = mod(112, 144, 288, 32, 64, 64, name="i4d")(x, train)
        aux2 = None
        if self.aux_heads and train:
            aux2 = AuxiliaryClassifier(self.num_classes, dtype=d,
                                       bn=self.bn, name="aux2")(x, train)
        x = mod(256, 160, 320, 32, 128, 128, name="i4e")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2), padding="SAME")
        x = mod(256, 160, 320, 32, 128, 128, name="i5a")(x, train)
        x = mod(384, 192, 384, 48, 128, 128, name="i5b")(x, train)

        x = layers.global_avg_pool(x)
        x = nn.Dropout(0.4, deterministic=not train)(x)
        main = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        if aux1 is not None:
            return main, aux1, aux2
        return main


# ---------------------------------------------------------------------------
# Inception V3 (capability completion; reference file is a stub)
# ---------------------------------------------------------------------------


class _InceptionA(nn.Module):
    pool_features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        b1 = ConvBN(64, (1, 1), dtype=d, name="b1")(x, train)
        b5 = ConvBN(48, (1, 1), dtype=d, name="b5r")(x, train)
        b5 = ConvBN(64, (5, 5), dtype=d, name="b5")(b5, train)
        b3 = ConvBN(64, (1, 1), dtype=d, name="b3r")(x, train)
        b3 = ConvBN(96, (3, 3), dtype=d, name="b3a")(b3, train)
        b3 = ConvBN(96, (3, 3), dtype=d, name="b3b")(b3, train)
        bp = layers.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        bp = ConvBN(self.pool_features, (1, 1), dtype=d, name="bp")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class _InceptionB(nn.Module):  # grid reduction 35 -> 17
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        b3 = ConvBN(384, (3, 3), (2, 2), padding="VALID", dtype=d,
                    name="b3")(x, train)
        bd = ConvBN(64, (1, 1), dtype=d, name="bdr")(x, train)
        bd = ConvBN(96, (3, 3), dtype=d, name="bda")(bd, train)
        bd = ConvBN(96, (3, 3), (2, 2), padding="VALID", dtype=d,
                    name="bdb")(bd, train)
        bp = layers.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class _InceptionC(nn.Module):  # factorized 7x7
    c7: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d, c7 = self.dtype, self.c7
        b1 = ConvBN(192, (1, 1), dtype=d, name="b1")(x, train)
        b7 = ConvBN(c7, (1, 1), dtype=d, name="b7r")(x, train)
        b7 = ConvBN(c7, (1, 7), dtype=d, name="b7a")(b7, train)
        b7 = ConvBN(192, (7, 1), dtype=d, name="b7b")(b7, train)
        bb = ConvBN(c7, (1, 1), dtype=d, name="bbr")(x, train)
        bb = ConvBN(c7, (7, 1), dtype=d, name="bba")(bb, train)
        bb = ConvBN(c7, (1, 7), dtype=d, name="bbb")(bb, train)
        bb = ConvBN(c7, (7, 1), dtype=d, name="bbc")(bb, train)
        bb = ConvBN(192, (1, 7), dtype=d, name="bbd")(bb, train)
        bp = layers.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        bp = ConvBN(192, (1, 1), dtype=d, name="bp")(bp, train)
        return jnp.concatenate([b1, b7, bb, bp], axis=-1)


class _InceptionD(nn.Module):  # grid reduction 17 -> 8
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        b3 = ConvBN(192, (1, 1), dtype=d, name="b3r")(x, train)
        b3 = ConvBN(320, (3, 3), (2, 2), padding="VALID", dtype=d,
                    name="b3")(b3, train)
        b7 = ConvBN(192, (1, 1), dtype=d, name="b7r")(x, train)
        b7 = ConvBN(192, (1, 7), dtype=d, name="b7a")(b7, train)
        b7 = ConvBN(192, (7, 1), dtype=d, name="b7b")(b7, train)
        b7 = ConvBN(192, (3, 3), (2, 2), padding="VALID", dtype=d,
                    name="b7c")(b7, train)
        bp = layers.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class _InceptionE(nn.Module):  # expanded-filter-bank output blocks
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        d = self.dtype
        b1 = ConvBN(320, (1, 1), dtype=d, name="b1")(x, train)
        b3 = ConvBN(384, (1, 1), dtype=d, name="b3r")(x, train)
        b3 = jnp.concatenate([
            ConvBN(384, (1, 3), dtype=d, name="b3a")(b3, train),
            ConvBN(384, (3, 1), dtype=d, name="b3b")(b3, train),
        ], axis=-1)
        bd = ConvBN(448, (1, 1), dtype=d, name="bdr")(x, train)
        bd = ConvBN(384, (3, 3), dtype=d, name="bda")(bd, train)
        bd = jnp.concatenate([
            ConvBN(384, (1, 3), dtype=d, name="bdb")(bd, train),
            ConvBN(384, (3, 1), dtype=d, name="bdc")(bd, train),
        ], axis=-1)
        bp = layers.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        bp = ConvBN(192, (1, 1), dtype=d, name="bp")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """299x299 input; returns logits (plus one aux logit tuple in training)."""

    num_classes: int = 1000
    aux_heads: bool = True
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        x = x.astype(d)
        x = ConvBN(32, (3, 3), (2, 2), padding="VALID", dtype=d, name="stem1")(x, train)
        x = ConvBN(32, (3, 3), padding="VALID", dtype=d, name="stem2")(x, train)
        x = ConvBN(64, (3, 3), dtype=d, name="stem3")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2))
        x = ConvBN(80, (1, 1), padding="VALID", dtype=d, name="stem4")(x, train)
        x = ConvBN(192, (3, 3), padding="VALID", dtype=d, name="stem5")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2))

        x = _InceptionA(32, dtype=d, name="a1")(x, train)
        x = _InceptionA(64, dtype=d, name="a2")(x, train)
        x = _InceptionA(64, dtype=d, name="a3")(x, train)
        x = _InceptionB(dtype=d, name="b")(x, train)
        x = _InceptionC(128, dtype=d, name="c1")(x, train)
        x = _InceptionC(160, dtype=d, name="c2")(x, train)
        x = _InceptionC(160, dtype=d, name="c3")(x, train)
        x = _InceptionC(192, dtype=d, name="c4")(x, train)
        aux = None
        if self.aux_heads and train:
            a = layers.avg_pool(x, (5, 5), (3, 3))
            a = ConvBN(128, (1, 1), dtype=d, name="aux_proj")(a, train)
            a = ConvBN(768, (5, 5), padding="VALID", dtype=d,
                       name="aux_conv")(a, train)
            a = a.reshape((a.shape[0], -1))
            aux = nn.Dense(self.num_classes, dtype=jnp.float32,
                           name="aux_fc")(a)
        x = _InceptionD(dtype=d, name="dd")(x, train)
        x = _InceptionE(dtype=d, name="e1")(x, train)
        x = _InceptionE(dtype=d, name="e2")(x, train)
        x = layers.global_avg_pool(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        main = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        if aux is not None:
            return main, aux
        return main


@register("inception1")
def _inception_v1(**kw):
    return InceptionV1(**kw)


@register("inception1_ref")
def _inception_v1_ref(**kw):
    """Reference-exact (BN-free) variant — the checkpoint-converter
    target (convert/torch_import.inception_torch_to_flax)."""
    kw.setdefault("bn", False)
    return InceptionV1(**kw)


@register("inception3")
def _inception_v3(**kw):
    return InceptionV3(**kw)
