"""Model registry: name -> Flax module factory (+ per-model policies).

Replaces the reference's per-trainer ``training_config`` model lookup
(ref: ResNet/pytorch/train.py:541-562 argparse choices) with one global
registry shared by the CLI, tests, converter, and benchmarks.

Since the HBM diet (ISSUE 15) a registration also DECLARES the model's
rematerialization policy — the activation-recompute schedule the deep
models trade FLOPs for HBM with (``jax.checkpoint`` through the module's
own ``remat`` field; ResNet ``"block"``/``"conv"``, Hourglass
``"stack"``). The registry only declares it: the TRAINING builders
(``train/configs.get_config`` → ``model_kwargs``) apply it, because
remat's ``prevent_cse`` optimization barriers belong in the train step's
backward, not in forward-only serving programs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

@dataclass(frozen=True)
class _Entry:
    factory: Callable
    remat: str | None = None


_REGISTRY: dict[str, _Entry] = {}


def register(name: str, *, remat: str | None = None):
    """Register a model factory; ``remat`` declares the model's default
    rematerialization policy (a value the factory's module must accept
    as its ``remat`` field)."""

    def deco(factory):
        if name in _REGISTRY:
            raise ValueError(f"duplicate model name {name!r}")
        _REGISTRY[name] = _Entry(factory, remat)
        return factory

    return deco


def get_model(name: str, **kwargs):
    try:
        entry = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    # bare callables tolerated: tests (and downstream monkeypatchers)
    # insert plain factories into _REGISTRY without the _Entry wrapper
    factory = entry.factory if isinstance(entry, _Entry) else entry
    return factory(**kwargs)


def model_remat(name: str) -> str | None:
    """The registry-declared remat policy for ``name`` (None when the
    model has none — or is unknown, so config plumbing can ask about
    CLI-only config aliases like the GAN trainers)."""
    entry = _REGISTRY.get(name)
    return entry.remat if isinstance(entry, _Entry) else None


def list_models() -> list[str]:
    return sorted(_REGISTRY)
