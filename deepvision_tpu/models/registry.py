"""Model registry: name -> Flax module factory.

Replaces the reference's per-trainer ``training_config`` model lookup
(ref: ResNet/pytorch/train.py:541-562 argparse choices) with one global
registry shared by the CLI, tests, converter, and benchmarks.
"""

from __future__ import annotations

from typing import Callable

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(factory):
        if name in _REGISTRY:
            raise ValueError(f"duplicate model name {name!r}")
        _REGISTRY[name] = factory
        return factory

    return deco


def get_model(name: str, **kwargs):
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(_REGISTRY)}"
        ) from None
    return factory(**kwargs)


def list_models() -> list[str]:
    return sorted(_REGISTRY)
