"""MobileNet V1 — depthwise-separable convolutions.

ref: MobileNet/pytorch/models/mobilenet_v1.py:10-156 (depthwise via
``groups=in_channels`` → here ``feature_group_count``) and the TF twin's
``SeparableConv2D`` = DW+BN+ReLU+PW+BN+ReLU (ref:
MobileNet/tensorflow/models/mobilenet_v1.py:7-74).

Reference defects fixed (SURVEY §"known defects"): the PT model's width
multiplier ``alpha`` only worked for integer values and the first BN was
hardcoded to 32 channels (ref: mobilenet_v1.py:30-31). Here ``alpha`` is a
proper float multiplier (paper semantics, channels rounded to int, min 8)
applied uniformly.

Depthwise convs are one of the Pallas-kernel candidates (SURVEY §2.5): XLA
lowers ``feature_group_count=C`` convs to the VPU rather than the MXU.
Measured on a v5e chip (round 2): 6,341 img/s/chip for the full bf16
train step at batch 256 — 11.8% MFU by XLA's own FLOP count, the
expected VPU-bound profile. A fused Pallas DW+BN+ReLU kernel remains a
possible (NOT yet implemented) bandwidth optimization; the shipped
Pallas kernel is the LRN one (ops/lrn_pallas.py).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.layers import ConvBN
from deepvision_tpu.models.registry import register


def _scale(ch: int, alpha: float) -> int:
    return max(8, int(ch * alpha))


class DepthwiseSeparableConv(nn.Module):
    """DW 3x3 (+BN+ReLU) then PW 1x1 (+BN+ReLU)."""

    features: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        # explicit symmetric padding: the reference's torch convs pad (1,1)
        # while XLA "SAME" pads (0,1) under stride 2 — activation parity
        # for the checkpoint converter (same convention as models/resnet.py)
        x = ConvBN(in_ch, (3, 3), (self.strides,) * 2, groups=in_ch,
                   padding=((1, 1), (1, 1)),
                   dtype=self.dtype, name="dw")(x, train)
        x = ConvBN(self.features, (1, 1), dtype=self.dtype, name="pw")(x, train)
        return x


class MobileNetV1(nn.Module):
    num_classes: int = 1000
    alpha: float = 1.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d, a = self.dtype, self.alpha
        x = x.astype(d)
        x = ConvBN(_scale(32, a), (3, 3), (2, 2),
                   padding=((1, 1), (1, 1)),  # torch pad parity (ref :31)
                   dtype=d, name="stem")(x, train)
        cfg = [  # (features, stride) per paper Table 1
            (64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
            (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
            (1024, 2), (1024, 1),
        ]
        for i, (f, s) in enumerate(cfg):
            x = DepthwiseSeparableConv(_scale(f, a), strides=s, dtype=d,
                                       name=f"ds{i + 1}")(x, train)
        x = layers.global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


@register("mobilenet1")
def _mobilenet_v1(**kw):
    return MobileNetV1(**kw)
