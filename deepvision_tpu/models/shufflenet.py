"""ShuffleNet V1 — grouped 1x1 convs + channel shuffle.

The reference never finished this: the model file is empty and the README
says "This is still WIP" (ref: ShuffleNet/pytorch/models/shufflenet_v1.py
[0 bytes], ShuffleNet/pytorch/README.md:1). Implemented here in full per the
paper (g=3 column: 240/480/960 channels, stages of 4/8/4 blocks) — a
CAPABILITY COMPLETION, flagged per SURVEY §2.1.

Channel shuffle is a pure layout op (reshape-transpose-reshape) that XLA
folds into the surrounding convs' layout assignments — free on TPU.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from deepvision_tpu.models import layers
from deepvision_tpu.models.layers import ConvBN
from deepvision_tpu.models.registry import register

_STAGE_CHANNELS = {1: 144, 2: 200, 3: 240, 4: 272, 8: 384}
_STAGE_BLOCKS = (4, 8, 4)


def channel_shuffle(x: jnp.ndarray, groups: int) -> jnp.ndarray:
    b, h, w, c = x.shape
    x = x.reshape(b, h, w, groups, c // groups)
    x = x.transpose(0, 1, 2, 4, 3)
    return x.reshape(b, h, w, c)


class ShuffleUnit(nn.Module):
    features: int  # output channels of the unit
    groups: int = 3
    strides: int = 1
    first_group: bool = True  # no groups on the 1x1 reduce of stage2 block1
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        out = self.features - (x.shape[-1] if self.strides == 2 else 0)
        # Bottleneck width is 1/4 of the unit's NOMINAL width (paper §3.2),
        # not of the concat-adjusted `out` — keeps `mid` divisible by the
        # group count for every paper column (g in {1,2,3,4,8}).
        mid = self.features // 4
        g1 = self.groups if self.first_group else 1
        y = ConvBN(mid, (1, 1), groups=g1, dtype=d, name="gconv1")(x, train)
        y = channel_shuffle(y, self.groups)
        y = ConvBN(mid, (3, 3), (self.strides,) * 2, groups=mid, act=None,
                   dtype=d, name="dwconv")(y, train)
        y = ConvBN(out, (1, 1), groups=self.groups, act=None,
                   dtype=d, name="gconv2")(y, train)
        if self.strides == 2:
            shortcut = layers.avg_pool(x, (3, 3), (2, 2), padding="SAME")
            return nn.relu(jnp.concatenate([shortcut, y], axis=-1))
        return nn.relu(x + y)


class ShuffleNetV1(nn.Module):
    num_classes: int = 1000
    groups: int = 3
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        d = self.dtype
        x = x.astype(d)
        x = ConvBN(24, (3, 3), (2, 2), dtype=d, name="stem")(x, train)
        x = layers.max_pool(x, (3, 3), (2, 2), padding="SAME")
        base = _STAGE_CHANNELS[self.groups]
        for stage, n_blocks in enumerate(_STAGE_BLOCKS):
            feats = base * (2 ** stage)
            for j in range(n_blocks):
                x = ShuffleUnit(
                    feats,
                    groups=self.groups,
                    strides=2 if j == 0 else 1,
                    first_group=not (stage == 0 and j == 0),
                    dtype=d,
                    name=f"stage{stage + 2}_unit{j + 1}",
                )(x, train)
        x = layers.global_avg_pool(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


@register("shufflenet1")
def _shufflenet_v1(**kw):
    return ShuffleNetV1(**kw)
