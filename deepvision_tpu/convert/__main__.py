"""Converter CLI: reference checkpoint file → Orbax checkpoint.

    python -m deepvision_tpu.convert <ckpt.pt|ckpt.h5> -m <model> -o <workdir>

Reads a reference PyTorch ``.pt`` (dict-of-everything or state dict,
DataParallel prefixes handled — ref: ResNet/pytorch/train.py:417-428) or a
Keras ``.h5`` and writes ``<workdir>/<model>/ckpt`` in the framework's own
Orbax layout, directly consumable by ``evaluate.py``/``predict.py``
(``--workdir <workdir> -m <model>``).

Family dispatch by model name:
  resnet34/resnet50/resnet152   torch stage/block naming
  vgg16/vgg19, alexnet2, lenet5 Sequential layer order (+ NCHW flatten fix)
  inception1_ref                BN-free parity variant incl. aux heads
  mobilenet1                    dw/pw separable-conv naming
  resnet50v2                    keras-applications HDF5 naming
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

import numpy as np


# (layer list, flatten grid at the conv→linear boundary) per Sequential net
_SEQUENTIAL = {
    "vgg16": ("VGG16_LAYERS", (7, 7)),
    "vgg19": ("VGG19_LAYERS", (7, 7)),
    "alexnet2": ("ALEXNET2_LAYERS", (6, 6)),
}


def convert_file(path: str, model_name: str, num_classes: int = 1000):
    """-> Flax variables dict for ``model_name``."""
    from deepvision_tpu.convert import torch_import as ti

    if path.endswith((".h5", ".hdf5")):
        if model_name != "resnet50v2":
            raise SystemExit(
                f"h5 conversion is wired for resnet50v2, not {model_name}"
            )
        from deepvision_tpu.convert.keras_import import keras_h5_to_flax

        return keras_h5_to_flax(path)

    sd = ti.load_torch_checkpoint(path)
    if model_name in ("resnet34", "resnet50", "resnet152"):
        return ti.resnet_torch_to_flax(sd)
    if model_name == "inception1":
        raise SystemExit(
            "reference Inception V1 weights are BN-free — convert with "
            "-m inception1_ref (the reference-exact model variant)"
        )
    if model_name == "inception1_ref":
        return ti.inception_torch_to_flax(sd)
    if model_name == "mobilenet1":
        return ti.mobilenet_torch_to_flax(sd)
    if model_name in _SEQUENTIAL:
        layers_name, grid = _SEQUENTIAL[model_name]
        return ti.sequential_torch_to_flax(
            sd, getattr(ti, layers_name), flatten_grid=grid
        )
    raise SystemExit(f"no converter family map for model {model_name!r}")


def save_as_checkpoint(variables: dict, model_name: str, workdir: str,
                       num_classes: int, input_size: int, channels: int):
    """Wrap converted variables in a TrainState and write epoch 0 through
    the framework's CheckpointManager (restore via restore_inference)."""
    import optax

    from deepvision_tpu.models import get_model
    from deepvision_tpu.train.checkpoint import CheckpointManager
    from deepvision_tpu.train.state import create_train_state

    model = get_model(model_name, num_classes=num_classes)
    sample = np.zeros((1, input_size, input_size, channels), np.float32)
    state = create_train_state(model, optax.sgd(0.1), sample)

    def check_tree(template, got, coll):
        t_paths = {p for p, _ in _leaves(template)}
        g_paths = {p for p, _ in _leaves(got)}
        if t_paths != g_paths:
            missing = sorted(t_paths - g_paths)[:8]
            extra = sorted(g_paths - t_paths)[:8]
            raise SystemExit(
                f"{coll} tree mismatch for {model_name}: "
                f"missing={missing} extra={extra}"
            )

    check_tree(state.params, variables["params"], "params")
    if state.batch_stats:
        check_tree(state.batch_stats, variables.get("batch_stats", {}),
                   "batch_stats")
    state = state.replace(
        params=variables["params"],
        batch_stats=variables.get("batch_stats", state.batch_stats) or
        state.batch_stats,
    )
    out = Path(workdir) / model_name / "ckpt"
    mgr = CheckpointManager(out)
    mgr.save(0, state, extra={"converted_from": "reference-checkpoint"})
    mgr.close()
    return out


def _leaves(tree, prefix=()):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _leaves(v, prefix + (k,))
    else:
        yield "/".join(prefix), tree


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m deepvision_tpu.convert", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("checkpoint", help=".pt/.h5 reference checkpoint file")
    p.add_argument("-m", "--model", required=True)
    p.add_argument("-o", "--workdir", required=True,
                   help="output workdir (evaluate.py --workdir)")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--input-size", type=int, default=224)
    p.add_argument("--channels", type=int, default=3)
    args = p.parse_args(argv)

    variables = convert_file(args.checkpoint, args.model, args.num_classes)
    out = save_as_checkpoint(
        variables, args.model, args.workdir,
        args.num_classes, args.input_size, args.channels,
    )
    n_params = sum(
        int(np.prod(np.shape(v))) for _, v in _leaves(variables["params"])
    )
    print(f"converted {args.checkpoint} -> {out} "
          f"({n_params:,} params, model={args.model})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
