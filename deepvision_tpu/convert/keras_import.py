"""Keras HDF5 weights → Flax variables.

Covers the reference's two Keras checkpoint forms (SURVEY §5.4):
full-model/weights HDF5 saved per epoch (ref: ResNet/tensorflow/
train.py:65-78) and keras-applications pretrained files ingested by hash
(ref: ResNet/tensorflow/models/resnet50v2.py:137-153). Keras kernels are
already (KH, KW, I, O) / (I, O) — no transpose; BN gamma/beta/moving_*
map to scale/bias/mean/var.

The name mapping implemented here is the keras-applications ResNet50V2
scheme (``conv{s}_block{j}_{k}_conv`` etc.) → ``models.resnet.ResNetV2``.
"""

from __future__ import annotations

import re
from typing import Callable

import numpy as np

from deepvision_tpu.convert.torch_import import _set


def _read_h5_weights(path) -> dict[str, np.ndarray]:
    """save_weights-format HDF5 -> {"layer/weight:0": array}."""
    import h5py

    out = {}

    def visit(name, obj):
        if isinstance(obj, h5py.Dataset):
            out[name] = np.asarray(obj)

    with h5py.File(path, "r") as f:
        root = f["model_weights"] if "model_weights" in f else f
        root.visititems(visit)
    return out


_BN_LEAF = {
    "gamma": ("params", "scale"),
    "beta": ("params", "bias"),
    "moving_mean": ("batch_stats", "mean"),
    "moving_variance": ("batch_stats", "var"),
}
_CONV_LEAF = {"kernel": ("params", "kernel"), "bias": ("params", "bias")}


def _resnet50v2_key(name: str):
    """keras dataset path -> (collection, flax path) or None."""
    # dataset paths look like "conv1_conv/conv1_conv/kernel:0"
    parts = name.split("/")
    layer, leaf = parts[0], parts[-1].split(":")[0]
    m = re.fullmatch(r"conv(\d)_block(\d+)_(preact_bn|\d_conv|\d_bn)", layer)
    if m:
        stage, block, rest = m.groups()
        base = f"stage{int(stage) - 1}_block{block}"
        if rest == "preact_bn":
            coll, out_leaf = _BN_LEAF[leaf]
            return coll, (base, "preact_bn", out_leaf)
        idx, kind = rest.split("_")
        if kind == "conv":
            sub = "proj" if idx == "0" else f"conv{idx}"
            coll, out_leaf = _CONV_LEAF[leaf]
            return coll, (base, sub, out_leaf)
        coll, out_leaf = _BN_LEAF[leaf]
        return coll, (base, f"bn{idx}", out_leaf)
    if layer == "conv1_conv":
        coll, out_leaf = _CONV_LEAF[leaf]
        return coll, ("stem", out_leaf)
    if layer == "post_bn":
        coll, out_leaf = _BN_LEAF[leaf]
        return coll, ("post_bn", out_leaf)
    if layer == "predictions":
        coll, out_leaf = _CONV_LEAF[leaf]
        return coll, ("fc", out_leaf)
    return None


def keras_h5_to_flax(
    path, key_fn: Callable = _resnet50v2_key
) -> dict:
    """HDF5 weight file -> {'params': ..., 'batch_stats': ...}."""
    out: dict[str, dict] = {"params": {}, "batch_stats": {}}
    misses = []
    for name, value in _read_h5_weights(path).items():
        spec = key_fn(name)
        if spec is None:
            misses.append(name)
            continue
        coll, flax_path = spec
        _set(out[coll], flax_path, value.astype(np.float32))
    if misses:
        raise KeyError(f"unmapped keras weights: {misses[:10]}")
    return out
