"""PyTorch checkpoint → Flax variables (the north-star converter).

Handles the reference's checkpoint-dict-of-everything
(``{'epoch','model','optimizer','scheduler','loggers'}`` —
ref: ResNet/pytorch/train.py:417-428), bare state dicts, and the
``nn.DataParallel`` ``module.`` key prefix
(ref: ResNet/pytorch/README.md:85-93). Layout conversion:

- conv weights (O, I, KH, KW) → (KH, KW, I, O),
- linear weights (O, I) → (I, O),
- BN weight/bias → scale/bias params; running_mean/var → batch_stats.

Name translation is per-architecture-family; the ResNet family mapping
covers the reference's naming (``conv{2..5}x.{j}.conv{k}/bn{k}``,
``projection.0/1``, ``linear`` — ref: ResNet/pytorch/models/resnet50.py).
No torch import is needed unless reading a ``.pt`` file — conversion
itself operates on numpy arrays.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Mapping

import numpy as np


def strip_module_prefix(state_dict: Mapping) -> dict:
    """Drop DataParallel's ``module.`` prefix (ref: README.md:85-93)."""
    return {
        (k[len("module."):] if k.startswith("module.") else k): v
        for k, v in state_dict.items()
    }


def load_torch_checkpoint(path) -> dict:
    """Read a ``.pt`` file → numpy state dict (handles the reference's
    full-checkpoint dict and raw state dicts)."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    if isinstance(obj, dict) and "model" in obj and isinstance(
        obj["model"], dict
    ):
        obj = obj["model"]  # ref: train.py:417-428 schema
    if hasattr(obj, "state_dict"):
        obj = obj.state_dict()
    return {
        k: np.asarray(v.detach().cpu().numpy())
        for k, v in strip_module_prefix(obj).items()
    }


def _to_numpy(v):
    if hasattr(v, "detach"):
        v = v.detach().cpu().numpy()
    return np.asarray(v)


def _set(tree: dict, path: tuple[str, ...], value) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


_BN_FIELDS = {
    "weight": ("params", "scale", lambda v: v),
    "bias": ("params", "bias", lambda v: v),
    "running_mean": ("batch_stats", "mean", lambda v: v),
    "running_var": ("batch_stats", "var", lambda v: v),
}


def _resnet_key(key: str):
    """reference torch key -> (collection, flax path, transform) or None."""
    conv_t = lambda v: v.transpose(2, 3, 1, 0)
    if key == "conv1.weight":
        return "params", ("stem", "conv", "kernel"), conv_t
    m = re.fullmatch(r"bn1\.(\w+)", key)
    if m and m.group(1) in _BN_FIELDS:
        coll, leaf, f = _BN_FIELDS[m.group(1)]
        return coll, ("stem", "bn", leaf), f
    m = re.fullmatch(
        r"conv(\d)x\.(\d+)\.(conv|bn)(\d)\.(\w+)", key
    )
    if m:
        stage, block, kind, k, field = m.groups()
        base = (f"stage{int(stage) - 1}_block{int(block) + 1}", f"conv{k}")
        if kind == "conv":
            return "params", base + ("conv", "kernel"), conv_t
        if field in _BN_FIELDS:
            coll, leaf, f = _BN_FIELDS[field]
            return coll, base + ("bn", leaf), f
        return None  # num_batches_tracked
    m = re.fullmatch(
        r"conv(\d)x\.(\d+)\.projection\.([01])\.(\w+)", key
    )
    if m:
        stage, block, idx, field = m.groups()
        base = (f"stage{int(stage) - 1}_block{int(block) + 1}", "proj")
        if idx == "0":
            return "params", base + ("conv", "kernel"), conv_t
        if field in _BN_FIELDS:
            coll, leaf, f = _BN_FIELDS[field]
            return coll, base + ("bn", leaf), f
        return None
    if key == "linear.weight":
        return "params", ("fc", "kernel"), lambda v: v.T
    if key == "linear.bias":
        return "params", ("fc", "bias"), lambda v: v
    return None


def torch_to_flax(
    state_dict: Mapping, key_fn: Callable[[str], Any] = _resnet_key
) -> dict:
    """state dict -> {'params': ..., 'batch_stats': ...} (f32 numpy).

    Unmapped keys raise so silent coverage gaps can't produce a model with
    randomly-initialized leftovers.
    """
    out: dict[str, dict] = {"params": {}, "batch_stats": {}}
    skipped = []
    for key, value in strip_module_prefix(dict(state_dict)).items():
        spec = key_fn(key)
        if spec is None:
            skipped.append(key)
            continue
        coll, path, transform = spec
        _set(out[coll], path, transform(_to_numpy(value)).astype(np.float32))
    hard_misses = [
        k for k in skipped if not k.endswith("num_batches_tracked")
    ]
    if hard_misses:
        raise KeyError(f"unmapped torch keys: {hard_misses[:10]}")
    return out


def resnet_torch_to_flax(state_dict: Mapping) -> dict:
    """Reference ResNet-34/50/152 torch weights → Flax variables for
    ``models.resnet`` (same mapping covers all three depths)."""
    return torch_to_flax(state_dict, _resnet_key)


def sequential_torch_to_flax(
    state_dict: Mapping,
    layer_names: list[str],
    *,
    flatten_grid: tuple[int, int] | None = None,
) -> dict:
    """Ordered conv/linear torch nets (VGG/AlexNet/LeNet families, whose
    state dicts are ``features.N``/``classifier.N`` Sequential keys —
    ref: VGG/pytorch/models/vgg16.py, AlexNet/pytorch/models/alexnet_v2.py)
    → Flax variables, zipping the torch modules in order with
    ``layer_names``.

    ``flatten_grid``: the (H, W) of the activation entering the first
    linear layer. torch flattens NCHW (C·H·W order) while the Flax models
    flatten NHWC, so that weight's input dimension is permuted
    C,H,W → H,W,C before transposing.
    """
    sd = {
        k: _to_numpy(v)
        for k, v in strip_module_prefix(dict(state_dict)).items()
    }
    prefixes: list[str] = []
    for k in sd:
        p = k.rsplit(".", 1)[0]
        if p not in prefixes:
            prefixes.append(p)
    if len(prefixes) != len(layer_names):
        raise ValueError(
            f"{len(prefixes)} torch layers vs {len(layer_names)} names"
        )
    params: dict = {}
    prev_channels = None
    first_linear = True
    for prefix, name in zip(prefixes, layer_names):
        if f"{prefix}.bias" not in sd:
            raise KeyError(
                f"{prefix}: no bias — sequential mapping covers "
                "conv/linear layers with bias only"
            )
        w = sd[f"{prefix}.weight"].astype(np.float32)
        b = sd[f"{prefix}.bias"].astype(np.float32)
        if w.ndim == 4:  # conv (O, I, KH, KW) -> (KH, KW, I, O)
            kernel = w.transpose(2, 3, 1, 0)
            prev_channels = w.shape[0]
        elif w.ndim == 2:  # linear (O, I) -> (I, O)
            if first_linear and prev_channels is not None:
                # the conv→linear boundary: torch flattened NCHW, the
                # Flax models flatten NHWC — permute or fail LOUDLY
                # (a silent skip would scramble the fc weights)
                if flatten_grid is None:
                    if w.shape[1] != prev_channels:
                        raise ValueError(
                            f"{prefix}: in_features {w.shape[1]} != "
                            f"{prev_channels} conv channels — this net "
                            "flattens a spatial grid; pass flatten_grid"
                        )
                else:
                    h, wd = flatten_grid
                    if w.shape[1] != prev_channels * h * wd:
                        raise ValueError(
                            f"{prefix}: in_features {w.shape[1]} != "
                            f"{prev_channels}·{h}·{wd} — wrong "
                            "flatten_grid for this architecture"
                        )
                    w = (
                        w.reshape(w.shape[0], prev_channels, h, wd)
                        .transpose(0, 2, 3, 1)
                        .reshape(w.shape[0], -1)
                    )
            first_linear = False
            kernel = w.T
        else:
            raise ValueError(
                f"{prefix}: unsupported weight rank {w.ndim} "
                "(BatchNorm-style layers need a per-architecture key_fn, "
                "see torch_to_flax)"
            )
        params[name] = {"kernel": kernel, "bias": b}
    return {"params": params, "batch_stats": {}}


# Layer orders for the reference's Sequential architectures.
VGG16_LAYERS = [
    "conv1_1", "conv1_2", "conv2_1", "conv2_2",
    "conv3_1", "conv3_2", "conv3_3",
    "conv4_1", "conv4_2", "conv4_3",
    "conv5_1", "conv5_2", "conv5_3",
    "fc1", "fc2", "fc3",
]
VGG19_LAYERS = [
    "conv1_1", "conv1_2", "conv2_1", "conv2_2",
    "conv3_1", "conv3_2", "conv3_3", "conv3_4",
    "conv4_1", "conv4_2", "conv4_3", "conv4_4",
    "conv5_1", "conv5_2", "conv5_3", "conv5_4",
    "fc1", "fc2", "fc3",
]
ALEXNET2_LAYERS = [
    "conv1", "conv2", "conv3", "conv4", "conv5", "fc6", "fc7", "fc8",
]


def _mobilenet_key(key: str):
    """Reference MobileNet V1 torch keys → models.mobilenet paths
    (ref: MobileNet/pytorch/models/mobilenet_v1.py:27-87: ``features.0``
    stem conv, ``features.1`` stem BN, ``features.{3..15}`` 13
    DepthwiseSeparableConvs with dw/pw conv+bn children, ``linear`` head).

    Depthwise kernels: torch (C, 1, KH, KW) with ``groups=C`` →
    Flax ``feature_group_count`` layout (KH, KW, 1, C) — the same
    (2, 3, 1, 0) transpose as dense convs.
    """
    conv_t = lambda v: v.transpose(2, 3, 1, 0)
    if key == "features.0.weight":
        return "params", ("stem", "conv", "kernel"), conv_t
    m = re.fullmatch(r"features\.1\.(\w+)", key)
    if m and m.group(1) in _BN_FIELDS:
        coll, leaf, f = _BN_FIELDS[m.group(1)]
        return coll, ("stem", "bn", leaf), f
    m = re.fullmatch(r"features\.(\d+)\.(dw|pw)\.conv\.weight", key)
    if m:
        idx, branch = m.groups()
        return ("params",
                (f"ds{int(idx) - 2}", branch, "conv", "kernel"), conv_t)
    m = re.fullmatch(r"features\.(\d+)\.(dw|pw)\.bn\.(\w+)", key)
    if m:
        idx, branch, field = m.groups()
        if field in _BN_FIELDS:
            coll, leaf, f = _BN_FIELDS[field]
            return coll, (f"ds{int(idx) - 2}", branch, "bn", leaf), f
        return None  # num_batches_tracked
    if key == "linear.weight":
        return "params", ("fc", "kernel"), lambda v: v.T
    if key == "linear.bias":
        return "params", ("fc", "bias"), lambda v: v
    return None


def mobilenet_torch_to_flax(state_dict: Mapping) -> dict:
    """Reference MobileNet V1 torch weights → Flax variables."""
    return torch_to_flax(state_dict, _mobilenet_key)


_INCEPTION_STEM = {"conv7x7": "stem1", "conv1x1": "stem2", "conv3x3": "stem3"}
_INCEPTION_BRANCH = {
    "branch1_conv1x1": "b1",
    "branch2_conv1x1": "b3r", "branch2_conv3x3": "b3",
    "branch3_conv1x1": "b5r", "branch3_conv5x5": "b5",
    "branch4_conv1x1": "bp",
}


def _aux_fc1_weight(v):
    """The reference flattens the aux 4×4×128 activation NCHW (C-major,
    ref: inception_v1.py:185-189) while the Flax model flattens NHWC —
    permute the input dimension C,H,W → H,W,C before transposing."""
    out = v.shape[0]
    return (v.reshape(out, 128, 4, 4).transpose(0, 2, 3, 1)
            .reshape(out, -1).T)


def _inception_key(key: str):
    """Reference Inception V1 torch keys → models.inception paths for the
    ``bn=False`` parity variant (conv+bias blocks — the reference's
    BasicConv2d has NO BatchNorm, ref: inception_v1.py:193-200; aux heads
    ref: inception_v1.py:161-190)."""
    conv_t = lambda v: v.transpose(2, 3, 1, 0)
    m = re.fullmatch(r"(conv7x7|conv1x1|conv3x3)\.conv\.(weight|bias)", key)
    if m:
        name, field = m.groups()
        leaf = ("kernel", conv_t) if field == "weight" else ("bias", lambda v: v)
        return "params", (_INCEPTION_STEM[name], "conv", leaf[0]), leaf[1]
    m = re.fullmatch(
        r"inception_(\d[a-e])\.(branch\d_conv\dx\d)\.conv\.(weight|bias)", key
    )
    if m:
        mod, branch, field = m.groups()
        leaf = ("kernel", conv_t) if field == "weight" else ("bias", lambda v: v)
        return ("params",
                (f"i{mod}", _INCEPTION_BRANCH[branch], "conv", leaf[0]),
                leaf[1])
    m = re.fullmatch(r"aux([12])\.features\.1\.conv\.(weight|bias)", key)
    if m:
        idx, field = m.groups()
        leaf = ("kernel", conv_t) if field == "weight" else ("bias", lambda v: v)
        return "params", (f"aux{idx}", "proj", "conv", leaf[0]), leaf[1]
    m = re.fullmatch(r"aux([12])\.classifier\.([03])\.(weight|bias)", key)
    if m:
        idx, layer, field = m.groups()
        name = "fc1" if layer == "0" else "fc2"
        if field == "bias":
            return "params", (f"aux{idx}", name, "bias"), lambda v: v
        if name == "fc1":
            return "params", (f"aux{idx}", "fc1", "kernel"), _aux_fc1_weight
        return "params", (f"aux{idx}", "fc2", "kernel"), lambda v: v.T
    if key == "linear.weight":
        return "params", ("fc", "kernel"), lambda v: v.T
    if key == "linear.bias":
        return "params", ("fc", "bias"), lambda v: v
    return None


def inception_torch_to_flax(state_dict: Mapping) -> dict:
    """Reference Inception V1 torch weights (incl. aux heads) → Flax
    variables for ``InceptionV1(bn=False)``."""
    return torch_to_flax(state_dict, _inception_key)
