"""Pretrained-weight ingestion with hash verification.

Capability parity with ref: ResNet/tensorflow/models/resnet50v2.py:137-153
— the reference downloads keras-applications release weights by URL and
verifies a file hash before loading. Here ingestion is file-first (this
framework runs in egress-restricted TPU environments): verify the
sha256/md5 of a local artifact against the expected digest, then hand it
to the matching importer (torch .pt / keras .h5). Downloading, when the
environment allows it, is the caller's concern (e.g. ``gsutil cp`` in the
launch tooling).
"""

from __future__ import annotations

import hashlib
from pathlib import Path


def file_digest(path: str | Path, algorithm: str = "sha256") -> str:
    with open(path, "rb") as fh:
        if hasattr(hashlib, "file_digest"):  # python >= 3.11
            return hashlib.file_digest(fh, algorithm).hexdigest()
        h = hashlib.new(algorithm)
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
        return h.hexdigest()


def verify_artifact(
    path: str | Path, expected_digest: str, algorithm: str = "sha256"
) -> Path:
    """Return ``path`` if its digest matches; raise otherwise (the
    reference's file_hash check, resnet50v2.py:146-151)."""
    path = Path(path)
    got = file_digest(path, algorithm)
    if got != expected_digest.lower():
        raise ValueError(
            f"{path} {algorithm} mismatch: got {got}, "
            f"expected {expected_digest}"
        )
    return path


def load_pretrained(
    path: str | Path,
    *,
    expected_digest: str | None = None,
    algorithm: str = "sha256",
):
    """Verified pretrained checkpoint → Flax variables.

    Dispatches on suffix: ``.pt``/``.pth`` → convert.torch_import,
    ``.h5``/``.hdf5`` → convert.keras_import.
    """
    path = Path(path)
    if expected_digest is not None:
        verify_artifact(path, expected_digest, algorithm)
    suffix = path.suffix.lower()
    if suffix in (".pt", ".pth"):
        from deepvision_tpu.convert.torch_import import (
            load_torch_checkpoint,
            resnet_torch_to_flax,
        )

        return resnet_torch_to_flax(load_torch_checkpoint(path))
    if suffix in (".h5", ".hdf5"):
        from deepvision_tpu.convert.keras_import import keras_h5_to_flax

        return keras_h5_to_flax(path)
    raise ValueError(f"unrecognized checkpoint format: {path.name}")
