"""Checkpoint conversion + activation diffing (SURVEY §5.4 north star).

- ``torch_import``: reference PyTorch checkpoints (dict-of-everything,
  DataParallel prefixes, NCHW) → Flax variables.
- ``keras_import``: Keras HDF5 (per-epoch full-model saves,
  keras-applications pretrained files) → Flax variables.
- ``diff``: layer-for-layer activation comparison between the converted
  Flax model and the source torch module.
"""

from deepvision_tpu.convert.diff import diff_activations, resnet_name_map
from deepvision_tpu.convert.keras_import import keras_h5_to_flax
from deepvision_tpu.convert.torch_import import (
    inception_torch_to_flax,
    load_torch_checkpoint,
    mobilenet_torch_to_flax,
    resnet_torch_to_flax,
    strip_module_prefix,
    torch_to_flax,
)

__all__ = [
    "diff_activations",
    "resnet_name_map",
    "keras_h5_to_flax",
    "inception_torch_to_flax",
    "load_torch_checkpoint",
    "mobilenet_torch_to_flax",
    "resnet_torch_to_flax",
    "strip_module_prefix",
    "torch_to_flax",
]
