"""Layer-for-layer activation diffing (the north-star verification tool).

``diff_activations`` runs the SAME image through the Flax model (with
``capture_intermediates``) and a source torch module (with forward hooks),
aligns activations by a name map, and reports max-abs-error per layer —
the tool the reference never had for checking its own pytorch↔tensorflow
pairs (SURVEY §0 north star).
"""

from __future__ import annotations

from typing import Mapping

import jax
import numpy as np


def _flax_intermediates(model, variables, image_nhwc) -> dict[str, np.ndarray]:
    _, state = model.apply(
        variables,
        image_nhwc,
        train=False,
        capture_intermediates=True,
        mutable=["intermediates"],
    )

    flat = {}

    def walk(node, path):
        if isinstance(node, Mapping):
            for k, v in node.items():
                walk(v, path + (k,))
        else:
            name = "/".join(p for p in path if p != "__call__")
            for leaf in (
                node if isinstance(node, (tuple, list)) else (node,)
            ):
                if hasattr(leaf, "shape"):
                    flat.setdefault(name, np.asarray(leaf))

    walk(state["intermediates"], ())
    return flat


def _torch_intermediates(module, image_nchw) -> dict[str, np.ndarray]:
    import torch

    acts: dict[str, np.ndarray] = {}
    hooks = []
    for name, sub in module.named_modules():
        if name:
            hooks.append(
                sub.register_forward_hook(
                    lambda m, i, o, name=name: acts.__setitem__(
                        name,
                        o.detach().cpu().numpy()
                        if hasattr(o, "detach") else None,
                    )
                )
            )
    try:
        module.eval()
        with torch.no_grad():
            out = module(torch.from_numpy(image_nchw))
        acts["__output__"] = out.detach().cpu().numpy()
    finally:
        for h in hooks:
            h.remove()
    return acts


def _nchw_to_nhwc(a: np.ndarray) -> np.ndarray:
    return a.transpose(0, 2, 3, 1) if a.ndim == 4 else a


def diff_activations(
    model, variables, torch_module, image_nhwc, name_map: Mapping[str, str]
) -> dict[str, float]:
    """-> {flax layer name: max abs err vs the mapped torch module output}.

    ``name_map``: flax intermediate path (e.g. ``"stage1_block1"``) →
    torch module name (e.g. ``"conv2x.0"``). The special flax key
    ``"__output__"`` compares final outputs.
    """
    image_nhwc = np.asarray(image_nhwc, np.float32)
    flax_acts = _flax_intermediates(model, variables, image_nhwc)
    flax_acts["__output__"] = np.asarray(
        model.apply(variables, image_nhwc, train=False)
    )
    torch_acts = _torch_intermediates(
        torch_module, image_nhwc.transpose(0, 3, 1, 2)
    )
    report = {}
    for flax_name, torch_name in name_map.items():
        a = flax_acts.get(flax_name)
        b = torch_acts.get(torch_name)
        if a is None or b is None:
            report[flax_name] = float("nan")
            continue
        b = _nchw_to_nhwc(b)
        if a.shape != b.shape:
            report[flax_name] = float("inf")
            continue
        report[flax_name] = float(
            np.max(np.abs(a.astype(np.float32) - b.astype(np.float32)))
        )
    return report


def resnet_name_map(stage_sizes=(3, 4, 6, 3)) -> dict[str, str]:
    """Default flax→torch map for the reference ResNet family
    (block outputs + logits)."""
    out = {"__output__": "__output__"}
    for s, n in enumerate(stage_sizes):
        for j in range(n):
            out[f"stage{s + 1}_block{j + 1}"] = f"conv{s + 2}x.{j}"
    return out
